"""Spark-interop IPC codecs (zstd/lz4 frames) + the FileSystem seam.

≙ reference common/ipc_compression.rs:30-335 (zstd level 1 / LZ4 frame
per spark.io.compression.codec) and datafusion-ext-commons/src/
hadoop_fs.rs:26-160 (all scan IO through registered FS callbacks).
"""

import io
import os

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import conf
from blaze_tpu.io.ipc_compression import (
    compress_frame,
    decompress_frame,
    lz4_frame_compress,
    lz4_frame_decompress,
)

PAYLOAD = (b"the quick brown fox " * 500) + bytes(range(256)) * 10


@pytest.mark.parametrize("codec", ["zlib", "zstd", "lz4", "raw"])
def test_frame_roundtrip(codec):
    frame = compress_frame(PAYLOAD, codec)
    assert decompress_frame(frame) == PAYLOAD


def test_zstd_interop_with_zstandard_frames():
    """Frames from any standard zstd writer decode (the reference's
    zstd::Encoder emits the same format)."""
    import struct

    import zstandard

    comp = zstandard.ZstdCompressor(level=1).compress(PAYLOAD)
    frame = struct.pack("<IB", len(comp), 2) + comp
    assert decompress_frame(frame) == PAYLOAD


def test_lz4_frame_interop_with_pyarrow():
    """Our LZ4 frames decode with pyarrow's LZ4 frame codec, and
    pyarrow-compressed frames decode with ours — the reference's
    lz4_flex frames are the same format."""
    codec = pa.Codec("lz4")
    # ours -> pyarrow
    ours = lz4_frame_compress(PAYLOAD)
    assert codec.decompress(ours, decompressed_size=len(PAYLOAD)).to_pybytes() == PAYLOAD
    # pyarrow -> ours (compressed blocks, possibly linked)
    theirs = codec.compress(PAYLOAD).to_pybytes()
    assert lz4_frame_decompress(theirs) == PAYLOAD


def test_shuffle_file_with_zstd_codec(tmp_path):
    """End-to-end: shuffle .data files written under
    spark.io.compression.codec=zstd read back correctly."""
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.parallel.exchange import NativeShuffleExchangeExec
    from blaze_tpu.parallel.shuffle import HashPartitioning
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema

    old = conf.IO_COMPRESSION_CODEC.get()
    try:
        conf.IO_COMPRESSION_CODEC.set("zstd")
        schema = Schema([Field("k", DataType.int64()), Field("v", DataType.string(8))])
        data = {"k": list(range(64)), "v": [f"s{i}" for i in range(64)]}
        b = batch_from_pydict(data, schema)
        ex = NativeShuffleExchangeExec(MemoryScanExec([[b]], schema), HashPartitioning([col("k")], 4))
        rows = []
        for p in range(4):
            for ob in ex.execute(p, TaskContext(p, 4)):
                d = batch_to_pydict(ob)
                rows += list(zip(d["k"], d["v"]))
        assert sorted(rows) == sorted(zip(data["k"], data["v"]))
    finally:
        conf.IO_COMPRESSION_CODEC.set(old)


# ------------------------------------------------------------- FS seam

def test_local_fs_and_scheme_resolution(tmp_path):
    from blaze_tpu.io.fs import get_fs

    p = tmp_path / "x.bin"
    fs = get_fs(str(p))
    with fs.create(str(p)) as f:
        f.write(b"hello")
    assert fs.exists(str(p)) and fs.size(str(p)) == 5
    with fs.open(f"file://{p}") as f:
        assert f.read() == b"hello"


def test_callback_fs_parquet_scan(tmp_path):
    """A parquet scan through a registered callback FS — the
    positioned-read contract of hadoop_fs.rs (reads cross the callback
    per seek window, no local path ever opened)."""
    import pyarrow.parquet as papq

    from blaze_tpu.batch import batch_to_pydict, concat_batches
    from blaze_tpu.io.fs import CallbackFileSystem, register_fs, unregister_fs
    from blaze_tpu.ops import ParquetScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema

    local = tmp_path / "remote.parquet"
    table = pa.table({"x": pa.array(list(range(100)), pa.int64())})
    papq.write_table(table, local, compression="snappy")
    blob = local.read_bytes()

    calls = {"n": 0}

    def open_cb(path):
        assert path.startswith("mockfs://")

        def pread(pos, n):
            calls["n"] += 1
            return blob[pos : pos + n]

        return pread, len(blob)

    register_fs("mockfs", CallbackFileSystem(open_cb))
    try:
        scan = ParquetScanExec([["mockfs://bucket/remote.parquet"]],
                               Schema([Field("x", DataType.int64())]))
        out = list(scan.execute(0, TaskContext(0, 1)))
        d = batch_to_pydict(concat_batches(out))
        assert d["x"] == list(range(100))
        assert calls["n"] >= 2  # footer + data crossed the callback
    finally:
        unregister_fs("mockfs")


def test_callback_fs_orc_scan(tmp_path):
    from pyarrow import orc as paorc

    from blaze_tpu.batch import batch_to_pydict, concat_batches
    from blaze_tpu.io.fs import CallbackFileSystem, register_fs, unregister_fs
    from blaze_tpu.ops.orc_scan import OrcScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema

    local = tmp_path / "remote.orc"
    table = pa.table({"x": pa.array(list(range(77)), pa.int64())})
    paorc.write_table(table, local, compression="zlib")
    blob = local.read_bytes()

    def open_cb(path):
        def pread(pos, n):
            return blob[pos : pos + n]

        return pread, len(blob)

    register_fs("mockfs", CallbackFileSystem(open_cb))
    try:
        scan = OrcScanExec([["mockfs://b/remote.orc"]], Schema([Field("x", DataType.int64())]))
        out = list(scan.execute(0, TaskContext(0, 1)))
        d = batch_to_pydict(concat_batches(out))
        assert d["x"] == list(range(77))
    finally:
        unregister_fs("mockfs")
