"""Multi-process pseudo-distributed testenv.

≙ the reference's ``dev/testenv`` (SURVEY §4 tier 3): the same query
runs as separate OS processes — one worker per task — against real
parquet input files and real shuffle files in a shared directory.
Every boundary is the production one: TaskDefinition protobuf bytes in,
``.data``/``.index`` shuffle files between stages, serde frames out.
"""

import base64
import json
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.io.batch_serde import deserialize_batch
from blaze_tpu.ops import MemoryScanExec, ParquetScanExec, ParquetSinkExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.runtime.scheduler import build_task, split_stages
from blaze_tpu.parallel.shuffle import LocalShuffleManager
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.spark import BlazeSparkSession

import spark_fixtures as F

pytestmark = pytest.mark.slow

SCHEMA = Schema([
    Field("l_quantity", DataType.int64()),
    Field("l_extendedprice", DataType.int64()),
    Field("l_discount", DataType.int64()),
])


def _write_parquet_inputs(tmp_path, n_files=3, rows=150):
    rng = np.random.RandomState(13)
    files, data = [], {"l_quantity": [], "l_extendedprice": [], "l_discount": []}
    for i in range(n_files):
        d = {
            "l_quantity": [int(v) for v in rng.randint(1, 50, rows)],
            "l_extendedprice": [int(v) for v in rng.randint(100, 10000, rows)],
            "l_discount": [int(v) for v in rng.randint(0, 10, rows)],
        }
        for k in data:
            data[k].extend(d[k])
        src = MemoryScanExec([[batch_from_pydict(d, SCHEMA)]], SCHEMA)
        path = str(tmp_path / f"lineitem_{i}.parquet")
        sink = ParquetSinkExec(src, path)
        for _ in sink.execute(0, TaskContext(0, 1)):
            pass
        files.append(sink.written_files[0] if sink.written_files else path)
    return files, data


def _run_worker(spec: dict, tmp_path, tag: str) -> None:
    spec_path = str(tmp_path / f"spec_{tag}.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONPATH", os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, "-m", "blaze_tpu.runtime.worker", spec_path],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]


def test_multi_process_two_stage_query(tmp_path):
    files, data = _write_parquet_inputs(tmp_path)

    # one parquet file per scan partition
    scan = ParquetScanExec([[f] for f in files], SCHEMA)
    sess = BlazeSparkSession()
    sess.register_table("lineitem", scan)

    s = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_extendedprice", 2), F.attr("l_discount", 3)])
    f = F.filter_(
        F.binop("And",
                F.binop("LessThan", F.attr("l_quantity", 1), F.lit(24, "long")),
                F.binop("GreaterThanOrEqual", F.attr("l_discount", 3), F.lit(5, "long"))),
        s,
    )
    pr = F.project(
        [F.alias(F.binop("Multiply", F.attr("l_extendedprice", 2), F.attr("l_discount", 3)), "rev", 10)],
        f,
    )
    partial = F.hash_agg([], [F.agg_expr(F.sum_(F.attr("rev", 10)), "Partial", 20)], pr)
    ex = F.shuffle(F.single_partition(), partial)
    final = F.hash_agg(
        [], [F.agg_expr(F.sum_(F.attr("rev", 10)), "Final", 20)], ex,
        result=[F.alias(F.attr("s", 20), "revenue", 21)],
    )
    plan_json = F.flatten(final)
    expected = sum(
        p * d for q, p, d in zip(data["l_quantity"], data["l_extendedprice"], data["l_discount"])
        if q < 24 and d >= 5
    )

    # driver: convert + split; every TASK runs in its own PROCESS
    shuffle_root = str(tmp_path / "shuffle")
    manager = LocalShuffleManager(shuffle_root)
    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan, manager)
    n_maps = {}
    results = []
    for stage in stages:
        for t in range(stage.n_tasks):
            output = (
                None
                if stage.kind == "map"
                else str(tmp_path / f"result_{stage.stage_id}_{t}.frames")
            )
            _, td = build_task(stage, manager, t)
            readers = [
                {"resource_id": f"shuffle_{sid}", "shuffle_id": sid, "n_maps": nm}
                for sid, nm in n_maps.items()
            ]
            spec = {
                "task_def": base64.b64encode(td).decode(),
                "partition": t,
                "shuffle_root": shuffle_root,
                "readers": readers,
                "output": output,
            }
            _run_worker(spec, tmp_path, f"{stage.stage_id}_{t}")
            if output:
                results.append(output)
        if stage.kind == "map":
            n_maps[stage.shuffle_id] = stage.n_tasks

    got = []
    out_schema = stages[-1].plan.schema
    from blaze_tpu.runtime.worker import read_result_frames

    for path in results:
        # the shared verified reader: per-frame checksums + the block
        # trailer (result files are standard checksummed IPC frames)
        for b in read_result_frames(path, out_schema):
            got.extend(batch_to_pydict(b)[out_schema.names[0]])
    assert got == [expected]
