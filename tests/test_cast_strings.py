"""Device string<->numeric/date casts, differential vs python oracles
(Spark non-ANSI semantics: malformed/overflowing input -> NULL).
Closes the cast tier's host-fallback gap (≙ cast.rs string paths)."""

import datetime

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

RNG = np.random.RandomState(11)


def _cast_strings(values, to, width=32):
    schema = Schema([Field("s", DataType.string(width))])
    src = MemoryScanExec([[batch_from_pydict({"s": values}, schema)]], schema)
    plan = ProjectExec(src, [col("s").cast(to).alias("r")])
    out = list(plan.execute(0, TaskContext(0, 1)))[0]
    return batch_to_pydict(out)["r"]


def _cast_to_string(values, src_t, width=32):
    """values are PHYSICAL (unscaled ints for decimals)."""
    from blaze_tpu.batch import RecordBatch, column_from_numpy

    n = len(values)
    valid = np.array([v is not None for v in values])
    phys = np.array([0 if v is None else v for v in values],
                    src_t.np_dtype if not src_t.is_decimal else np.int64)
    c = column_from_numpy(src_t, phys, validity=valid)
    src = MemoryScanExec([[RecordBatch(Schema([Field("v", src_t)]), [c], n)]],
                         Schema([Field("v", src_t)]))
    plan = ProjectExec(src, [col("v").cast(DataType.string(width)).alias("r")])
    out = list(plan.execute(0, TaskContext(0, 1)))[0]
    return batch_to_pydict(out)["r"]


def test_string_to_int_vs_python():
    vals = ["42", " -17 ", "+8", "0", "9223372036854775807",
            "-9223372036854775808", "9223372036854775808",   # overflow
            "3.7", "-3.7", "abc", "", "  ", "1e3", "--5", "12a",
            "1 2", "- 5", None, "00042", "-0"]
    got = _cast_strings(vals, DataType.int64())
    # Spark UTF8String.toLong: trims, single dot truncates the
    # validated fraction, interior junk/spaces null
    exp = [42, -17, 8, 0, 2**63 - 1, -(2**63), None,
           3, -3, None, None, None, None, None, None,
           None, None, None, 42, 0]
    assert got == exp


def test_string_to_int32_range_nulls():
    vals = ["2147483647", "2147483648", "-2147483648", "-2147483649"]
    got = _cast_strings(vals, DataType.int32())
    assert got == [2147483647, None, -2147483648, None]


def test_string_to_decimal_half_up():
    to = DataType.decimal(10, 2)
    vals = ["1.005", "-1.005", "3", "3.1", "3.14159", ".5", "-.25",
            "12345678.90", "99999999999", "x", "", None, "1.2.3"]
    got = _cast_strings(vals, to)
    import decimal as D
    def py(s):
        if s is None or s.strip() == "":
            return None
        try:
            d = D.Decimal(s.strip())
        except D.InvalidOperation:
            return None
        u = int(d.scaleb(2).quantize(D.Decimal(1), rounding=D.ROUND_HALF_UP))
        return u if abs(u) < 10**10 else None
    assert got == [py(v) for v in vals]


def test_string_to_bool():
    vals = ["true", "FALSE", " t ", "no", "Y", "1", "0", "maybe", "", None]
    got = _cast_strings(vals, DataType.bool_())
    assert got == [True, False, True, False, True, True, False, None, None, None]


def test_string_to_date_strict_iso():
    vals = ["1994-01-01", "2000-02-29", "1970-01-01", "1969-12-31",
            "2015-13-01", "2015-00-10", "20150101", "2015-1-1", "garbage", None]
    got = _cast_strings(vals, DataType.date32())
    def py(s):
        if s is None:
            return None
        try:
            d = datetime.date.fromisoformat(s)
        except ValueError:
            return None
        if len(s) != 10:
            return None
        return (d - datetime.date(1970, 1, 1)).days
    exp = [py(v) for v in vals]
    # out-of-range month/day null out (python raises too)
    assert got == exp


def test_string_to_date_calendar_invalid_nulls():
    vals = ["2021-02-28", "2021-02-29", "2020-02-29", "2021-02-30",
            "2000-04-31", "1900-02-29"]
    got = _cast_strings(vals, DataType.date32())
    def py(s):
        try:
            return (datetime.date.fromisoformat(s)
                    - datetime.date(1970, 1, 1)).days
        except ValueError:
            return None
    assert got == [py(v) for v in vals]


def test_int_to_string_width_overflow_nulls():
    got = _cast_to_string([123456789, 123], DataType.int64(), width=8)
    assert got == [None, "123"]


def test_int_to_string_roundtrip():
    vals = [0, 1, -1, 42, -9999, 2**62, -(2**63), 2**63 - 1,
            1234567890123456789]
    got = _cast_to_string(vals, DataType.int64())
    assert got == [str(v) for v in vals]


def test_decimal_to_string_keeps_scale():
    t = DataType.decimal(12, 2)
    unscaled = [0, 5, 50, 150, -5, -150, 123456, -1, 100]
    got = _cast_to_string(unscaled, t)
    exp = ["0.00", "0.05", "0.50", "1.50", "-0.05", "-1.50",
           "1234.56", "-0.01", "1.00"]
    assert got == exp


def test_bool_and_date_to_string():
    got = _cast_to_string([True, False, None], DataType.bool_())
    assert got == ["true", "false", None]
    days = [(datetime.date(1994, 1, 1) - datetime.date(1970, 1, 1)).days,
            0,
            (datetime.date(2024, 2, 29) - datetime.date(1970, 1, 1)).days]
    got = _cast_to_string(days, DataType.date32())
    assert got == ["1994-01-01", "1970-01-01", "2024-02-29"]


def test_randomized_int_roundtrip():
    vals = RNG.randint(-(2**62), 2**62, 300).tolist()
    strs = [str(v) for v in vals]
    assert _cast_strings(strs, DataType.int64()) == vals
    assert _cast_to_string(vals, DataType.int64()) == strs


def test_randomized_decimal_roundtrip():
    t = DataType.decimal(15, 3)
    unscaled = RNG.randint(-(10**12), 10**12, 300).tolist()
    strs = _cast_to_string(unscaled, t)
    back = _cast_strings(strs, t)
    assert back == unscaled
