"""Performance introspection layer (tier-1, CPU backend) —
runtime/perf.py: EXPLAIN ANALYZE, roofline/MFU attribution, and the
perf-baseline regression gate.

1. **EXPLAIN ANALYZE** (acceptance): a real warm TPC-H q01 run through
   the stage scheduler yields an explain tree that attributes >= 80%
   of the query wall to plan nodes, with per-node rows/bytes/batches
   populated and reconciling against the driver-observed output.
2. **Roofline math**: classify() unit-checked against a synthetic peak
   table (hbm_util / mfu_est / ridge-point bound selection), peak-table
   matching (longest substring, default fallback), and the estimator's
   pytree walk over real Column batches.
3. **Bound differentials**: q01/q06 classify dispatch-bound with
   hbm_util < 10% on this backend (the VERDICT r5 observation,
   reproduced mechanically); collapsing an unfused run's program count
   to the fused run's under the remote chip's per-program floor flips
   dispatch-bound -> memory-or-compute-bound.
4. **Perf-baseline gate**: --perfcheck machinery passes on HEAD over
   the TPC-H slice, FIRES on a seeded 2x dispatch inflation, and
   --perfcheck --update round-trips (re-pin then clean).
5. **Estimator cost contract**: disarmed, the dispatch choke point
   never enters the estimator (poisoned — one bool read, the
   trace.enabled pattern); armed, a real program records nonzero
   bytes/flops.
6. **Monitor endpoint**: /queries/<id>/explain serves the rendered
   explain for a traced run, a comment for an untraced one, 404 for an
   unknown query.
7. **Terminal-status rendering**: --report (text + JSON) renders
   cleanly — explicit status banner, no KeyError — over event logs of
   queries that ended failed / cancelled / deadline_exceeded, and over
   a truncated log with no terminal event at all.
8. **Golden pins**: EXPLAIN_JSON_KEYS / PERFCHECK_JSON_KEYS top-level
   shapes, and the --report --json ``perf`` section.
"""

import json
import shutil
import urllib.error
import urllib.request

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime import dispatch, monitor, perf, trace, trace_report
from blaze_tpu.runtime.context import (
    QueryCancelledError, QueryDeadlineError,
)
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

SCALE = 0.01
BATCH_ROWS = 4096


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


def _scans(data, n_parts=1, batch_rows=BATCH_ROWS):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=batch_rows),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def _run_scheduler(data, q, n_parts=1, batch_rows=BATCH_ROWS):
    stages, manager = split_stages(
        build_query(q, _scans(data, n_parts, batch_rows), n_parts))
    return sum(b.num_rows for b in run_stages(stages, manager))


def _traced_run(data, q, tmp_path, query_id=None, warm_runs=1,
                batch_rows=None):
    """Warm ``q`` through the scheduler, then run it once more traced;
    returns the event list of the traced (warm) run.  The default
    batch size (2048) keeps the per-batch program loop long enough
    that the dispatch floor dominates decisively on the CPU backend —
    the same regime the real chip's ~70 ms per-program turnaround puts
    every batch size in (VERDICT r5)."""
    batch_rows = batch_rows or 2048
    for _ in range(warm_runs):
        _run_scheduler(data, q, batch_rows=batch_rows)
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    perf.reset()
    try:
        with trace.query(query_id or f"perf_{q}") as path:
            rows = _run_scheduler(data, q, batch_rows=batch_rows)
        assert rows > 0 and path is not None
        return trace.read_events(path)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


# ------------------------------------------------- 1. EXPLAIN ANALYZE

@pytest.fixture(scope="module")
def q1_events(data, tmp_path_factory):
    return _traced_run(data, "q1",
                       tmp_path_factory.mktemp("explain_q1"))


@pytest.fixture(scope="module")
def q6_events(data, tmp_path_factory):
    return _traced_run(data, "q6",
                       tmp_path_factory.mktemp("explain_q6"))


def test_explain_q1_attributes_80pct_of_wall(q1_events):
    """Acceptance: the metric-annotated plan attributes >= 80% of a
    warm q01's query wall to plan nodes (the PR 3 reconciliation bar,
    applied to the explain tree)."""
    doc = perf.explain_doc(q1_events)
    assert doc["status"] == "done"
    assert doc["wall_ns"] > 0
    assert doc["attributed_pct"] >= 80.0, (
        f"only {doc['attributed_pct']}% of query wall attributed to "
        f"plan nodes")


def test_explain_q1_node_annotations_reconcile(q1_events):
    """Per-node rows/bytes/batches annotations are real: the scan node
    carries the full lineitem row count over > 1 batch with > 0 bytes,
    and row counts shrink monotonically through the aggregation."""
    doc = perf.explain_doc(q1_events)
    stage0 = next(s for s in doc["stages"] if s["stage_id"] == 0)
    assert stage0["plan"] is not None

    nodes = []

    def walk(n):
        nodes.append(n)
        for c in n["children"]:
            walk(c)

    walk(stage0["plan"])
    scan = next(n for n in nodes if n["op"] == "MemoryScanExec")
    assert scan["rows"] > 10_000          # the q01 lineitem scan
    assert scan["batches"] > 1
    assert scan["bytes"] > scan["rows"]   # > 1 byte per row, trivially
    agg = next(n for n in nodes if n["op"].startswith("AggExec"))
    assert 0 < agg["rows"] < scan["rows"]
    # own-time attribution present on the compute-carrying node
    assert agg["own_ns"] > 0


def test_explain_render_text(q1_events):
    text = perf.render_explain(q1_events)
    assert "EXPLAIN ANALYZE" in text
    assert "status=DONE" in text
    assert "MemoryScanExec" in text and "AggExec" in text
    assert "rows=" in text and "bytes=" in text and "batches=" in text
    assert "hbm_util=" in text and "mfu_est=" in text


def test_explain_fused_chain_marker(tmp_path):
    """A traceable chain that fuses into a FusedStageExec (the
    explode -> filter -> computed-projection chain the dispatch-budget
    suite pins as fusing) shows the fused-chain marker — op name,
    ``fused`` flag, and chain length — in its explain tree."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.exprs.ir import Alias, BinOp, Lit
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.generate import GenerateExec, NativeGenerator
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.schema import DataType, Field, Schema

    arr_t = DataType.array(DataType.int64(), 4)
    schema = Schema([Field("k", DataType.int64()), Field("xs", arr_t)])
    rows = {"k": list(range(40)),
            "xs": [[i, i + 1, i + 2][: (i % 4)] or None
                   for i in range(40)]}

    def plan():
        scan = MemoryScanExec([[batch_from_pydict(rows, schema)]], schema)
        g = GenerateExec(scan, NativeGenerator("explode", col("xs")),
                         [col("xs")])
        f = FilterExec(g, BinOp(">", col("col"),
                                Lit(5, DataType.int64())))
        return ProjectExec(
            f, [col("k"), Alias(BinOp("+", col("col"),
                                      Lit(1, DataType.int64())), "c1")],
            ["k", "c1"])

    def run():
        stages, mgr = split_stages(plan())
        return sum(b.num_rows for b in run_stages(stages, mgr))

    run()
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with trace.query("fused_chain") as path:
            assert run() > 0
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    events = trace.read_events(path)
    doc = perf.explain_doc(events)
    nodes = []

    def walk(n):
        nodes.append(n)
        for c in n["children"]:
            walk(c)

    for s in doc["stages"]:
        if s["plan"]:
            walk(s["plan"])
    fused = [n for n in nodes if n.get("fused")]
    assert fused, [n["op"] for n in nodes]
    assert fused[0]["fused_ops"] >= 2
    assert "[fused" in perf.render_explain(events)


def test_explain_json_golden_keys(q1_events):
    """The --explain --json shape is API: pinned top-level keys (add
    freely, never rename), JSON-serializable as-is."""
    doc = perf.explain_doc(q1_events)
    assert set(perf.EXPLAIN_JSON_KEYS) <= set(doc)
    for st in doc["stages"]:
        assert {"stage_id", "kind", "status", "wall_ns", "pct_of_query",
                "plan"} <= set(st)
    assert doc["kernels"], "no kernel table"
    for v in doc["kernels"].values():
        assert {"programs", "hbm_util", "mfu_est", "bound"} <= set(v)
    json.dumps(doc)


# ------------------------------------------------- 2. roofline units

SYNTH_PEAKS = {"hbm_gbps": 100.0, "tflops": 1.0, "device": "synth"}


def test_classify_units_memory_bound():
    """1 s of device time moving 50 GB at a 100 GB/s roof = 50% HBM
    utilization; 0.1 Tflop at a 1 TF roof = 10% MFU; intensity 0.002
    flop/byte is far under the ridge (10) -> memory-bound."""
    out = perf.classify(device_ns=1_000_000_000, dispatch_ns=0,
                        bytes_est=50_000_000_000,
                        flops_est=100_000_000_000, peaks=SYNTH_PEAKS)
    assert out["hbm_util"] == pytest.approx(0.5)
    assert out["mfu_est"] == pytest.approx(0.1)
    assert out["bound"] == "memory-bound"


def test_classify_units_compute_bound():
    """Intensity above the ridge point (flops/bytes > peak_flops/
    peak_bw = 10) with device time dominating -> compute-bound."""
    out = perf.classify(device_ns=1_000_000_000, dispatch_ns=0,
                        bytes_est=1_000_000_000,
                        flops_est=500_000_000_000, peaks=SYNTH_PEAKS)
    assert out["intensity"] == pytest.approx(500.0)
    assert out["bound"] == "compute-bound"
    assert out["mfu_est"] == pytest.approx(0.5)


def test_classify_dispatch_bound_and_unknown():
    out = perf.classify(device_ns=1_000, dispatch_ns=1_000_000,
                        bytes_est=100, flops_est=100, peaks=SYNTH_PEAKS)
    assert out["bound"] == "dispatch-bound"
    # utilization over the ATTRIBUTED wall: a chip idling between
    # programs must not flatter itself with a device-seconds-only
    # denominator
    assert out["hbm_util"] < 0.01
    empty = perf.classify(0, 0, 0, 0, SYNTH_PEAKS)
    assert empty["bound"] == "unknown"
    assert empty["hbm_util"] == 0.0


def test_peaks_for_matching():
    table = {"default": {"hbm_gbps": 1.0, "tflops": 1.0},
             "devices": {"v5": {"hbm_gbps": 2.0, "tflops": 2.0},
                         "v5e": {"hbm_gbps": 3.0, "tflops": 3.0}}}
    # longest substring wins; matching is case-insensitive
    assert perf.peaks_for("TPU V5E chip 0", table)["hbm_gbps"] == 3.0
    assert perf.peaks_for("tpu v5 pod", table)["hbm_gbps"] == 2.0
    # unmatched falls back to default, stamped as such
    e = perf.peaks_for("TFRT_CPU_0", table)
    assert e["hbm_gbps"] == 1.0 and e["device"] == "default"


def test_estimator_counts_column_pytree_buffers():
    """The estimator must see through the engine's registered pytrees
    (batch.Column): a real column's data+validity buffers count, not
    an opaque 0."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("x", DataType.int64())])
    b = batch_from_pydict({"x": list(range(1000))}, schema)
    nbytes, flops = perf._estimate((tuple(b.columns), b.num_rows), {},
                                   None)
    assert nbytes >= 8 * 1000  # at least the int64 data buffer
    assert flops >= 1000


# ------------------------------------------- 3. bound differentials

def test_q1_q6_dispatch_bound_under_10pct_hbm(q1_events, q6_events):
    """Acceptance (VERDICT r5 reproduced mechanically): warm q01/q06
    classify dispatch-bound with hbm_util < 10%.  The judgment is made
    from REAL measured per-query totals (programs, bytes, flops,
    device time) under the target chip's measured ~70 ms per-program
    dispatch floor and v5e peaks — the hardware the VERDICT observed.
    The CPU host's own python-call dispatch split swings 2-3x with CI
    load (both directions), so asserting on it would test the host's
    scheduler, not the engine; the floor model is load-invariant while
    still grounded in this run's measured program counts and bytes.
    The measured run must still show the floor is REAL here too: a
    substantial dispatch share and single-digit HBM utilization."""
    floor_ns = 70_000_000  # per-program turnaround through the tunnel
    for events in (q1_events, q6_events):
        qp = perf.query_perf(events, device_kind="cpu")
        # measured on this host: far under the memory roof, and the
        # launch floor is a visible fraction of the attributed wall
        assert qp["hbm_util"] < 0.10, qp
        assert qp["dispatch_ns"] > 0.15 * (qp["dispatch_ns"]
                                           + qp["device_ns"]), qp
        # the chip-model judgment --report would render on the v5e:
        # same programs/bytes/flops, the measured per-program floor
        chip = perf.classify(qp["device_ns"],
                             qp["programs"] * floor_ns,
                             qp["hbm_bytes_est"], qp["flops_est"],
                             perf.peaks_for("v5e"))
        assert chip["bound"] == "dispatch-bound", chip
        assert chip["hbm_util"] < 0.10, chip


def test_fusion_collapse_flips_bound_class(q1_events):
    """The differential the gate exists to catch, over REAL measured
    q01 totals: at the measured (fused) split the query is
    dispatch-bound; multiplying the dispatch bill by the pre-fusion
    program blowup (~20x, the VERDICT r5 ~100-programs-per-batch
    pathology vs ~1 warm) keeps it decisively dispatch-bound, while
    collapsing the dispatch bill 20x FURTHER (fusing past the
    boundary, ROADMAP item 3) flips the classification to
    memory-or-compute-bound — same bytes, same device work: fusion
    removes launches, not arithmetic."""
    totals = perf.sum_kernel_rows(trace_report._kernel_rows(q1_events))
    assert totals["programs"] > 0 and totals["bytes_est"] > 0
    peaks = perf.peaks_for("cpu")
    # the pre-fusion pathology: ~20x the measured dispatch bill (the
    # VERDICT ~100-programs-per-batch blowup vs ~1 warm) must read
    # decisively dispatch-bound whatever this host's load did to the
    # measured split...
    unfused = perf.classify(totals["device_ns"],
                            totals["dispatch_ns"] * 20,
                            totals["bytes_est"], totals["flops_est"],
                            peaks)
    assert unfused["bound"] == "dispatch-bound"
    # ...and collapsing the bill 20x below the measured split (fusing
    # past the boundary, ROADMAP item 3) must flip the class: device
    # work now dominates, same bytes, same arithmetic
    collapsed = perf.classify(totals["device_ns"],
                              totals["dispatch_ns"] // 20,
                              totals["bytes_est"], totals["flops_est"],
                              peaks)
    assert collapsed["bound"] in ("memory-bound", "compute-bound")


def test_unfused_run_issues_more_programs(data, tmp_path):
    """Ground the differential's premise in a real run: fusion OFF
    issues strictly more programs for the same q06 work."""
    fused = perf.sum_kernel_rows(trace_report._kernel_rows(
        _traced_run(data, "q6", tmp_path, query_id="diff_fused")))
    conf.FUSION_ENABLE.set(False)
    try:
        unfused = perf.sum_kernel_rows(trace_report._kernel_rows(
            _traced_run(data, "q6", tmp_path, query_id="diff_unfused")))
    finally:
        conf.FUSION_ENABLE.set(True)
    assert unfused["programs"] > fused["programs"]
    assert unfused["bytes_est"] > 0 and fused["bytes_est"] > 0


def test_query_perf_prefers_log_device_stamp():
    """An event log analyzed OFFLINE is judged against the roof of the
    hardware that RAN it (the query_start ``device_kind`` stamp), not
    the analyzing host's — a v5e log on a CPU box must use v5e peaks."""
    events = [
        {"ts": 1.0, "type": "query_start", "query_id": "q",
         "device_kind": "TPU v5e chip 0"},
        {"ts": 2.0, "type": "stage_complete", "stage_id": 0,
         "kind": "map", "n_tasks": 1, "status": "ok", "wall_ns": 10,
         "programs": 1, "device_time_ns": 5, "dispatch_overhead_ns": 1,
         "compile_ns": 0,
         "kernels": {"agg": {"programs": 1, "device_ns": 5,
                             "dispatch_ns": 1, "compile_ns": 0,
                             "timed": 1, "bytes_est": 100,
                             "flops_est": 10}}},
        {"ts": 3.0, "type": "query_end", "query_id": "q",
         "status": "ok", "wall_ns": 10},
    ]
    qp = perf.query_perf(events)
    assert qp["device_kind"] == "TPU v5e chip 0"
    assert qp["peak"]["device"] == "v5e"
    assert perf.explain_doc(events)["perf"]["peak"]["device"] == "v5e"
    # a pre-stamp log falls back to the analyzing process's device
    legacy = [dict(e) for e in events]
    legacy[0].pop("device_kind")
    assert perf.query_perf(legacy)["device_kind"] \
        == perf.current_device_kind()


def test_real_log_carries_device_stamp(q1_events):
    assert perf.device_kind_from_events(q1_events)


# --------------------------------------------- 4. perf-baseline gate

@pytest.fixture(scope="module")
def perfcheck_result():
    """ONE real measurement sweep shared by the gate tests (tier-1
    budget: the sweep is 5 warm queries at pinned scale)."""
    rc, doc = perf.run_perfcheck()
    return rc, doc


def test_perfcheck_clean_on_head(perfcheck_result):
    """Acceptance: --perfcheck passes on HEAD over the TPC-H slice."""
    rc, doc = perfcheck_result
    assert rc == 0, doc["problems"]
    assert doc["ok"] is True
    assert len(doc["queries"]) >= 5
    for name, m in doc["queries"].items():
        assert m["warm_compiles"] == 0, (name, m)


def test_perfcheck_json_golden_keys(perfcheck_result):
    _, doc = perfcheck_result
    assert set(perf.PERFCHECK_JSON_KEYS) <= set(doc)
    for m in doc["queries"].values():
        assert {"warm_dispatches", "dispatches_per_batch", "programs",
                "warm_compiles", "bound", "hbm_util", "mfu_est"} <= set(m)
    json.dumps(doc)


def test_perfcheck_fires_on_seeded_dispatch_inflation(perfcheck_result):
    """Acceptance: a seeded 2x dispatch inflation is DETECTED — drift
    detection actually fires, it is not a tautology."""
    _, doc = perfcheck_result
    registry = perf.load_baselines()
    # same resolution run_perfcheck uses: conf override when nonzero,
    # else the registry's pinned tolerance
    tolerance = (float(conf.PERF_TOLERANCE.get())
                 or float(registry.get("tolerance", 0.25)))
    fired = 0
    for name, base in registry["queries"].items():
        measured = dict(doc["queries"][name])
        measured["warm_dispatches"] *= 2
        measured["programs"] *= 2
        problems = perf.check_query(name, measured, base, tolerance)
        assert problems, f"{name}: 2x inflation not detected"
        fired += len(problems)
    assert fired >= len(registry["queries"])


def test_perfcheck_improvement_also_drifts():
    """Drift is two-sided: a silent improvement must be re-pinned, not
    absorbed (the registry stays meaningful)."""
    base = {"warm_dispatches": 100, "programs": 100, "warm_compiles": 0,
            "bound": "dispatch-bound"}
    measured = {"warm_dispatches": 50, "programs": 50, "warm_compiles": 0,
                "bound": "dispatch-bound", "device_ns": 1,
                "dispatch_ns": 100}
    problems = perf.check_query("qx", measured, base, 0.25)
    assert problems and "improved" in problems[0]


def test_perfcheck_bound_flip_borderline_is_noise():
    """A bound-class flip across a borderline dispatch/device split —
    within 10x either way, or with neither side past the absolute
    magnitude floor — is measurement noise, not drift: a loaded CI
    host swings the CPU backend's split 4-8x and collapses a small
    query's device reading to near zero (q6 at perfcheck scale:
    device 0.14 ms vs dispatch 8.8 ms under full-suite load), while a
    dispatch-floor re-fragmentation moves the ratio over an order of
    magnitude AND the dispatch wall into the hundreds of ms."""
    base = {"warm_dispatches": 10, "programs": 10, "warm_compiles": 0,
            "bound": "dispatch-bound"}
    ms = 1_000_000
    for dev, disp in ((100 * ms, 90 * ms), (100 * ms, 11 * ms),
                      (100 * ms, 950 * ms),
                      # decisive RATIO but under the magnitude floor —
                      # the real q6 full-suite-load reading
                      (138589, 8841526)):
        noisy = {"warm_dispatches": 10, "programs": 10,
                 "warm_compiles": 0, "bound": "memory-bound",
                 "device_ns": dev, "dispatch_ns": disp}
        assert perf.check_query("qx", noisy, base, 0.25) == [], (dev, disp)
    decisive = {"warm_dispatches": 10, "programs": 10,
                "warm_compiles": 0, "bound": "memory-bound",
                "device_ns": 1000 * ms, "dispatch_ns": 10 * ms}
    problems = perf.check_query("qx", decisive, base, 0.25)
    assert problems and "flipped" in problems[0]


def test_perfcheck_rejects_update_plus_inflate():
    """The self-test hook must never be able to pin falsified counts
    as golden baselines."""
    with pytest.raises(ValueError, match="self-test"):
        perf.run_perfcheck(update=True, inflate=2.0)


def test_perfcheck_update_roundtrip(tmp_path, monkeypatch):
    """--perfcheck --update re-pins the registry (with provenance) and
    a subsequent check against the re-pinned registry is clean — the
    round-trip, run against canned measurements so it stays fast."""
    reg_path = tmp_path / "baselines.json"
    shutil.copy(perf.BASELINES_PATH, reg_path)
    canned = {"rows": 1, "warm_dispatches": 999, "dispatches_per_batch":
              9.9, "programs": 999, "warm_compiles": 0,
              "device_ns": 10, "dispatch_ns": 100,
              "hbm_bytes_est": 1000, "flops_est": 100,
              "hbm_util": 0.01, "mfu_est": 0.001,
              "bound": "dispatch-bound"}
    monkeypatch.setattr(perf, "measure_query",
                        lambda *a, **k: dict(canned))
    rc, _ = perf.run_perfcheck(update=True, registry_path=str(reg_path))
    assert rc == 0
    pinned = perf.load_baselines(str(reg_path))
    assert pinned["queries"]["q1"]["warm_dispatches"] == 999
    assert pinned["provenance"]["pinned_at"]
    assert pinned["provenance"]["device_kind"]
    # the re-pinned registry is immediately clean against the same
    # measurements...
    rc, doc = perf.run_perfcheck(registry_path=str(reg_path))
    assert rc == 0, doc["problems"]
    # ...and still fires on inflation against the new pins
    rc, doc = perf.run_perfcheck(registry_path=str(reg_path), inflate=2.0)
    assert rc == 1 and doc["problems"]


# ------------------------------------- 5. estimator cost contract

def test_disarmed_estimator_never_entered(monkeypatch):
    """spark.blaze.perf.estimates=false keeps the traced dispatch path
    out of the estimator entirely (poisoned — a single call would
    raise), exactly the trace.enabled structural-no-op pattern."""
    import jax

    fn = dispatch.instrument(jax.jit(lambda x: x + 1), "perfgate_t")
    x = np.arange(512)
    conf.PERF_ESTIMATES.set(False)
    perf.reset()
    try:
        assert perf.enabled() is False

        def poisoned(*a, **k):  # pragma: no cover — failure path
            raise AssertionError("estimator entered while disarmed")

        with monkeypatch.context() as m:
            m.setattr(perf, "_estimate", poisoned)
            with trace.kernel_capture() as sink:
                fn(x)
        assert sum(v.get("bytes_est", 0) for v in sink.values()) == 0
    finally:
        conf.PERF_ESTIMATES.set(True)
        perf.reset()
    # re-armed: the same call records nonzero estimates
    with trace.kernel_capture() as sink:
        fn(x)
    assert sum(v.get("bytes_est", 0) for v in sink.values()) >= x.nbytes
    assert sum(v.get("flops_est", 0) for v in sink.values()) >= x.size


def test_force_overrides_conf_and_env(monkeypatch):
    """perf.force(True) must win over BOTH conf and the env override
    (ConfEntry gives env precedence over .set, so the measurement
    surfaces that JUDGE estimates cannot force-arm through conf);
    reset() hands control back."""
    monkeypatch.setenv("BLAZE_PERF_ESTIMATES", "false")
    perf.reset()
    try:
        assert perf.enabled() is False
        perf.force(True)
        assert perf._ARMED is True and perf.enabled() is True
        perf.reset()
        assert perf.enabled() is False
    finally:
        monkeypatch.delenv("BLAZE_PERF_ESTIMATES")
        perf.reset()


def test_untraced_path_records_no_estimates():
    """Without a kernel capture the estimator is never consulted at
    all — the untraced hot path is untouched (counters only)."""
    import jax

    fn = dispatch.instrument(jax.jit(lambda x: x * 2), "perfgate_u")
    with dispatch.capture() as cap:
        fn(np.arange(64))
    assert cap.get("xla_dispatches") == 1
    assert cap.get("hbm_bytes_est", 0) == 0


def test_chaos_perf_gate_passes():
    """The --chaos structural gate for the estimator contract."""
    from blaze_tpu.__main__ import _check_perf_gate

    assert _check_perf_gate() == 0


# --------------------------------------------- 6. monitor endpoint

def test_monitor_explain_endpoint(data, tmp_path):
    conf.MONITOR_ENABLE.set(True)
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    monitor.reset()
    trace.reset()
    srv = None
    try:
        srv = monitor.MonitorServer(0).start()
        with monitor.query_span("explain_ep_q6", mode="scheduler"):
            assert _run_scheduler(data, "q6") > 0
        # untraced run alongside: explain must answer with a comment,
        # not a 500
        conf.TRACE_ENABLE.set(False)
        trace.reset()
        with monitor.query_span("explain_ep_untraced"):
            pass
        with urllib.request.urlopen(
                f"{srv.url}/queries/explain_ep_q6/explain", timeout=10) as r:
            body = r.read().decode()
        assert "EXPLAIN ANALYZE" in body
        assert "explain_ep_q6" in body
        with urllib.request.urlopen(
                f"{srv.url}/queries/explain_ep_untraced/explain",
                timeout=10) as r:
            body = r.read().decode()
        assert body.startswith("#") and "tracing" in body
        # the endpoint is discoverable + the registry carries the log
        with urllib.request.urlopen(f"{srv.url}/healthz", timeout=10) as r:
            hz = json.load(r)
        assert "/queries/<id>/explain" in hz["endpoints"]
        with urllib.request.urlopen(f"{srv.url}/queries", timeout=10) as r:
            snap = json.load(r)
        entry = next(q for q in snap["queries"]
                     if q["query_id"] == "explain_ep_q6")
        assert entry["eventlog"]
        # roofline gauges exported for the traced query
        with urllib.request.urlopen(f"{srv.url}/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert 'blaze_query_hbm_util{query="explain_ep_q6"}' in metrics
        assert 'blaze_query_bound{query="explain_ep_q6"' in metrics
    finally:
        if srv is not None:
            srv.shutdown()
        conf.MONITOR_ENABLE.set(False)
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        monitor.reset()
        trace.reset()


def test_monitor_explain_404_on_unknown(data):
    conf.MONITOR_ENABLE.set(True)
    monitor.reset()
    srv = None
    try:
        srv = monitor.MonitorServer(0).start()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{srv.url}/queries/no_such_query/explain", timeout=10)
        assert ei.value.code == 404
    finally:
        if srv is not None:
            srv.shutdown()
        conf.MONITOR_ENABLE.set(False)
        monitor.reset()


# ------------------------------- 7. terminal-status report rendering

def _terminal_events(data, tmp_path, exc, query_id):
    """A REAL partial event log: stage 0 completes, then the query
    dies with ``exc`` — the shape a cancelled/failed/deadline-exceeded
    chaos run leaves behind."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with pytest.raises(type(exc)):
            with trace.query(query_id) as path:
                _run_scheduler(data, "q6")  # real stage/task events
                raise exc
        return trace.read_events(path)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


@pytest.mark.parametrize("exc,status", [
    (QueryCancelledError("t", reason="cancel"), "cancelled"),
    (QueryDeadlineError("t", timeout_ms=5), "deadline_exceeded"),
    (RuntimeError("boom"), "failed"),
])
def test_report_renders_terminal_statuses(data, tmp_path, exc, status):
    """--report over a query that did NOT end done: explicit status
    banner, no KeyError, JSON terminal_status populated (regression:
    the renderer was only ever exercised on done runs)."""
    events = _terminal_events(data, tmp_path, exc,
                              f"term_{status}")
    text = trace_report.render(events)
    assert status.upper() in text
    assert "partial profile" in text
    doc = trace_report.render_json(events)
    assert doc["query"]["terminal_status"] == status
    json.dumps(doc, default=str)
    # the explain surface degrades identically
    edoc = perf.explain_doc(events)
    assert edoc["status"] == status
    assert status.upper() in perf.render_explain(events)


def test_report_renders_truncated_log(data, tmp_path):
    """A log with NO terminal event (crash mid-run / live read): both
    renderers still work and say INCOMPLETE."""
    events = _terminal_events(data, tmp_path, RuntimeError("x"),
                              "term_trunc")
    truncated = [e for e in events if e.get("type") != "query_end"]
    text = trace_report.render(truncated)
    assert "INCOMPLETE" in text
    doc = trace_report.render_json(truncated)
    assert doc["query"]["terminal_status"] == "incomplete"
    assert perf.explain_doc(truncated)["status"] == "incomplete"


def test_report_json_has_perf_section(q1_events):
    """--report --json carries the roofline judgment: golden 'perf'
    top-level key plus per-kernel hbm_util/mfu_est/bound fields."""
    doc = trace_report.render_json(q1_events)
    assert "perf" in doc
    p = doc["perf"]
    assert {"programs", "hbm_util", "mfu_est", "bound",
            "hbm_bytes_est", "flops_est", "device_kind"} <= set(p)
    assert p["programs"] > 0
    assert p["hbm_bytes_est"] > 0
    for v in doc["kernels"].values():
        assert {"bytes_est", "flops_est", "hbm_util", "bound"} <= set(v)
    # the text rendering carries the same judgment
    assert "perf:" in trace_report.render(q1_events)
