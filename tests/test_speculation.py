"""Tail-latency robustness: speculative attempts, wedge detection, and
partial map-stage re-runs.

The retry machinery (test_faults.py) proves recovery from tasks that
FAIL; this suite proves recovery from tasks that merely STRAGGLE — the
injected-latency ``slow<ms>`` fault entries (runtime/faults.py) are the
deterministic stand-in for a slow host/wedged kernel, and every
scenario asserts the query's results stay identical to the undisturbed
run while the recovery is visible in the scheduler counters, the event
log, and the live registry.
"""

import os
import threading
import time

import pytest

from blaze_tpu import conf
from blaze_tpu.runtime import faults, monitor, trace
from blaze_tpu.runtime.metrics import MetricNode
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.runtime.speculation import (
    SPEC_ATTEMPT_BASE, SpeculationPolicy,
)

import spark_fixtures as F
from test_spark_convert import make_session, q6_like_plan  # noqa: E402


def _attempt_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("blaze-attempt-") and t.is_alive()]


@pytest.fixture(autouse=True)
def _clean_speculation():
    """Every scenario starts disarmed and leaves nothing armed or
    running; a leaked attempt thread fails the NEXT test too, which is
    exactly the point."""
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.0)
    faults.reset()
    yield
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.1)
    conf.SPECULATION_ENABLE.set(False)
    conf.SPECULATION_MULTIPLIER.set(1.5)
    conf.SPECULATION_QUANTILE.set(0.75)
    conf.SPECULATION_MIN_RUNTIME.set(0.1)
    conf.SPECULATION_WEDGE_MS.set(0)
    conf.TASK_WEDGE_MS.set(0)
    conf.STAGE_TASK_CONCURRENCY.set(1)
    conf.MONITOR_HEARTBEAT_MS.set(1000)
    faults.reset()
    monitor.reset()
    deadline = time.monotonic() + 10
    while _attempt_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert _attempt_threads() == [], "attempt runner leaked threads"


def _scheduler_run(sess, plan_json, metrics=None):
    from blaze_tpu.batch import batch_to_pydict

    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)
    out = {f.name: [] for f in stages[-1].plan.schema.fields}
    for b in run_stages(stages, manager, metrics=metrics):
        d = batch_to_pydict(b)
        for k in out:
            out[k].extend(d[k])
    return out, manager


def _inject(spec: str) -> None:
    conf.FAULTS_SPEC.set(spec)
    faults.reset()


# ------------------------------------------------------- policy units

def test_policy_triggers():
    p = SpeculationPolicy(enabled=True, multiplier=2.0, quantile=0.5,
                          min_runtime=0.1, wedge_ms=200)
    # quantile: 2 of 4 must be done
    assert not p.should_speculate(10.0, [1.0], 4)
    assert p.should_speculate(2.5, [1.0, 1.2], 4)      # > 2 x median
    assert not p.should_speculate(1.9, [1.0, 1.2], 4)  # under multiplier
    assert not p.should_speculate(0.05, [0.01, 0.01], 4)  # min runtime
    assert p.is_spec_wedged(0.25) and not p.is_spec_wedged(0.15)
    off = SpeculationPolicy()
    assert not off.runner_needed()
    assert not off.should_speculate(100.0, [0.1, 0.1], 2)
    # each arming route forces the concurrent runner
    assert SpeculationPolicy(enabled=True).runner_needed()
    assert SpeculationPolicy(task_wedge_ms=100).runner_needed()
    assert SpeculationPolicy(concurrency=2).runner_needed()


def test_policy_from_conf_clamps():
    conf.SPECULATION_ENABLE.set(True)
    conf.SPECULATION_MULTIPLIER.set(0.3)   # < 1 would speculate on noise
    conf.SPECULATION_QUANTILE.set(7.0)
    conf.STAGE_TASK_CONCURRENCY.set(0)
    p = SpeculationPolicy.from_conf()
    assert p.enabled and p.multiplier == 1.0 and p.quantile == 1.0
    assert p.concurrency == 1


# ------------------------------------- concurrent runner, no disturbance

def test_concurrent_runner_matches_serial_results():
    """taskConcurrency > 1 alone (no speculation, no faults) must be a
    pure scheduling change: identical rows, no extra attempts."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    conf.STAGE_TASK_CONCURRENCY.set(3)
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("speculative_attempts") == 0
    assert m.metrics.get("task_retries") == 0
    # 3 map + 1 result task, one attempt each
    assert m.metrics.get("task_attempts") == 4


def test_concurrent_runner_broadcast_stage():
    """Regression (found by the concurrent TPC-H sweep): the broadcast
    build drains its child under a DERIVED TaskContext — a fresh one
    detaches from the attempt's ScopedResources view, so every task of
    a broadcast-consuming stage failed with 'resource broadcast_0.0
    not found' under the concurrent runner."""
    from blaze_tpu.schema import DataType, Field, Schema

    sess, data = make_session()
    dim_schema = Schema([
        Field("d_key", DataType.int64()),
        Field("d_name", DataType.string(16)),
    ])
    sess.register_table(
        "dim",
        {"d_key": list(range(10)), "d_name": [f"name{i}" for i in range(10)]},
        dim_schema,
    )
    fact = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_discount", 3)])
    dim = F.broadcast(F.scan("dim", [F.attr("d_key", 5), F.attr("d_name", 6)]))
    join = F.bhj([F.attr("l_discount", 3)], [F.attr("d_key", 5)],
                 "Inner", "right", fact, dim)
    plan_json = F.flatten(
        F.project([F.attr("l_quantity", 1), F.attr("d_name", 6)], join))
    baseline, _ = _scheduler_run(sess, plan_json)
    assert len(baseline["l_quantity"]) == len(data["l_quantity"])

    conf.STAGE_TASK_CONCURRENCY.set(3)
    got, _ = _scheduler_run(sess, plan_json)
    assert got == baseline


def test_concurrent_runner_still_retries_faults():
    """The retry/fetch-recovery contract survives the concurrent
    runner: an injected crash is retried (through the runner's
    DEFERRED backoff — the poll loop schedules the relaunch instead
    of sleeping inline), results identical."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    conf.STAGE_TASK_CONCURRENCY.set(3)
    conf.TASK_RETRY_BACKOFF.set(0.05)  # nonzero: exercises relaunch_at
    _inject("task.compute@2@a0")
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("task_retries") == 1


# ------------------------------------------------- speculative attempts

def _arm_speculation(wedge_ms=0, multiplier=1.2, quantile=0.25,
                     min_runtime=0.02, heartbeat_ms=25, concurrency=1):
    conf.SPECULATION_ENABLE.set(True)
    conf.SPECULATION_MULTIPLIER.set(multiplier)
    conf.SPECULATION_QUANTILE.set(quantile)
    conf.SPECULATION_MIN_RUNTIME.set(min_runtime)
    conf.SPECULATION_WEDGE_MS.set(wedge_ms)
    conf.MONITOR_HEARTBEAT_MS.set(heartbeat_ms)
    conf.STAGE_TASK_CONCURRENCY.set(concurrency)
    monitor.reset()


def test_speculative_attempt_wins_duration_trigger():
    """Acceptance core: a seeded straggler makes one map task slow
    relative to its completed siblings; the backup attempt races it
    through the atomic-rename commit seam, wins, and the results are
    byte-identical to the undisturbed run."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    # all 3 map tasks in flight; the LAST map-side commit sleeps 800ms,
    # so two siblings complete fast and the duration trigger fires
    _arm_speculation(wedge_ms=0, concurrency=3)
    _inject("shuffle.write@3@slow800")
    m = MetricNode()
    t0 = time.monotonic()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("speculative_attempts") == 1
    assert m.metrics.get("speculative_won") == 1
    assert m.metrics.get("speculative_lost") == 0
    # the whole query finished without serially waiting out the
    # straggler's sleep on the critical path... the loser is reaped in
    # the background, bounded by its own sleep
    assert time.monotonic() - t0 < 10


def test_speculative_attempt_wins_wedge_trigger():
    """A task wedged INSIDE its first batch of work (no driver-visible
    output, no drain deadline) is caught by heartbeat age and raced."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    # duration trigger effectively off (multiplier huge, quantile 1.0);
    # only the wedge path can launch the backup
    _arm_speculation(wedge_ms=150, multiplier=1000.0, quantile=1.0)
    _inject("shuffle.write@1@slow700")
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("speculative_attempts") == 1
    assert m.metrics.get("speculative_won") == 1


def test_speculation_events_reconcile_and_registry_rolls_back(tmp_path):
    """The observability half of the acceptance gate: with tracing and
    the live monitor armed, the speculative race leaves a reconciled
    event log (every start paired with won/lost), the loser's registry
    heartbeat state is rolled back (no inflated /queries rows), and no
    attempt thread outlives the run."""
    sess, data = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    _arm_speculation(wedge_ms=150, multiplier=1000.0, quantile=1.0)
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    conf.MONITOR_ENABLE.set(True)
    trace.reset()
    monitor.reset()
    _inject("shuffle.write@1@slow700")
    m = MetricNode()
    try:
        with monitor.query_span("spec_q", mode="scheduler") as log_path:
            got, _ = _scheduler_run(sess, plan_json, metrics=m)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        conf.MONITOR_ENABLE.set(False)
        trace.reset()

    assert got == baseline
    assert m.metrics.get("speculative_won") == 1

    from blaze_tpu.runtime import trace_report

    events = trace.read_event_log(log_path)
    spc = trace_report.reconcile_speculation(events)
    assert spc["speculated"] == 1 and spc["won"] == 1
    assert spc["reconciled"], spc["unpaired"]
    starts = [e for e in events
              if e["type"] == "speculative_attempt_start"]
    assert starts[0]["reason"] == "wedged"
    assert starts[0]["attempt"] >= SPEC_ATTEMPT_BASE
    # straggler provocation is on the record too
    assert any(e["type"] == "straggler_injected" for e in events)

    # registry: the run really landed (attempt threads carry the query
    # context), and no task entry carries the LOSER's rows on top of
    # the winner's — per-partition live rows stay bounded by the source
    snap = monitor.snapshot()
    q = next(q for q in snap["queries"] if q["query_id"] == "spec_q")
    assert q["status"] == "done" and q["stages"]
    map_st = next(st for st in q["stages"] if st["kind"] == "map")
    assert map_st["tasks_done"] == map_st["n_tasks"] == 3
    n_rows = len(data["l_quantity"])
    for st in q["stages"]:
        assert st["task_rows"] <= n_rows
        for p, entry in st["tasks"].items():
            assert entry["rows"] <= n_rows


# ------------------------------------------------- wedge-triggered retry

def test_wedged_task_is_failed_and_retried_without_speculation():
    """Satellite: the drain deadline only fires between driver-observed
    batches, so a task wedged inside its first batch was invisible to
    the retry machinery.  With spark.blaze.task.wedgeMs armed (and
    speculation OFF), heartbeat age fails and retries it."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    conf.TASK_WEDGE_MS.set(150)
    conf.MONITOR_HEARTBEAT_MS.set(25)
    monitor.reset()
    # the sleep sits at the map-side COMMIT: the task yields nothing to
    # the driver, so no cooperative deadline could ever see it
    _inject("shuffle.write@1@slow700")
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("task_timeouts") >= 1   # the wedge, as a timeout
    assert m.metrics.get("task_retries") >= 1
    assert m.metrics.get("speculative_attempts") == 0


def test_task_wedge_still_fires_with_speculation_enabled():
    """Review-found regression: with speculation ENABLED but unable to
    act on a wedge (speculation.wedgeMs=0 and the duration trigger
    unreachable), an armed spark.blaze.task.wedgeMs must still cancel
    and retry the wedged task — otherwise enabling speculation
    silently DISABLED wedge recovery and a wedged task hung the stage
    forever."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    _arm_speculation(wedge_ms=0, multiplier=1000.0, quantile=1.0)
    conf.TASK_WEDGE_MS.set(150)
    _inject("shuffle.write@1@slow700")
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("task_timeouts") >= 1
    assert m.metrics.get("task_retries") >= 1
    assert m.metrics.get("speculative_attempts") == 0


# --------------------------------------------------- partial map re-runs

def test_partial_rerun_only_missing_map_ids():
    """Acceptance: a fetch failure naming one lost map output re-runs
    ONLY that map task — map_tasks_rerun strictly less than the map
    stage's n_tasks — with reduce output unchanged."""
    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.serde import from_proto

    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)
    n_map_tasks = stages[0].n_tasks
    assert n_map_tasks == 3
    baseline, _ = _scheduler_run(sess, plan_json)

    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)
    lost_data, _lost_index = manager.map_output_paths(
        stages[0].shuffle_id, 1)
    real_run_task = from_proto.run_task
    state = {"calls": 0, "deleted": False}

    def losing(td, **kw):
        state["calls"] += 1
        if state["calls"] == n_map_tasks + 1 and not state["deleted"]:
            # first reduce task: its blocks are registered — now the
            # committed output of map task 1 vanishes (≙ an executor
            # dying between stages); the read must name map id 1
            os.unlink(lost_data)
            state["deleted"] = True
        return real_run_task(td, **kw)

    m = MetricNode()
    from_proto.run_task = losing
    try:
        out = {f.name: [] for f in stages[-1].plan.schema.fields}
        for b in run_stages(stages, manager, metrics=m):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    finally:
        from_proto.run_task = real_run_task
    assert state["deleted"]
    assert out == baseline
    assert m.metrics.get("fetch_failures") == 1
    assert m.metrics.get("map_stage_reruns") == 1
    # THE partial-rerun proof: one missing map id => one task re-run
    assert m.metrics.get("map_tasks_rerun") == 1
    assert m.metrics.get("map_tasks_rerun") < n_map_tasks
    # 3 maps + 1 rerun + 2 reduce attempts (failed + retried)
    assert m.metrics.get("task_attempts") == 6


def test_injected_fetch_fault_still_reruns_whole_stage():
    """An INJECTED fetch failure carries no map ids (the producer is
    fine; the read was poisoned) — recovery falls back to the full
    map-stage rerun, counted as all n_tasks."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    _inject("shuffle.fetch@1@a0")
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("map_stage_reruns") == 1
    assert m.metrics.get("map_tasks_rerun") == 3


def test_cancelled_attempt_never_commits_over_winner(tmp_path):
    """Chaos-sweep-found regression: a cancelled attempt whose CHILD
    exits cooperatively (yielding zero batches) used to sail past the
    per-batch cancellation check straight into write_output and
    overwrite the winner's committed shuffle file with an EMPTY one.
    The commit itself must be cancellation-guarded."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.parallel.shuffle import (
        LocalShuffleManager, ShuffleWriterExec, SinglePartitioning,
    )
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("x", DataType.int64())])
    manager = LocalShuffleManager(str(tmp_path))
    data_p, index_p = manager.map_output_paths(0, 0)

    # the winner's commit
    full = MemoryScanExec(
        [[batch_from_pydict({"x": list(range(64))}, schema)]], schema)
    for _ in ShuffleWriterExec(full, SinglePartitioning(),
                               data_p, index_p).execute(0, TaskContext(0, 1)):
        pass
    winner = (open(data_p, "rb").read(), open(index_p, "rb").read())
    assert len(winner[0]) > 0

    # the loser: already cancelled, child yields nothing (the
    # cooperative early exit every blocking op performs)
    cancelled = threading.Event()
    cancelled.set()
    empty = MemoryScanExec([[]], schema)
    for _ in ShuffleWriterExec(empty, SinglePartitioning(),
                               data_p, index_p).execute(
            0, TaskContext(0, 1, cancel_event=cancelled)):
        pass
    assert (open(data_p, "rb").read(), open(index_p, "rb").read()) == winner

    # a legitimately EMPTY, uncancelled task still commits (the reduce
    # barrier keys on index existence)
    d2, i2 = manager.map_output_paths(0, 1)
    for _ in ShuffleWriterExec(empty, SinglePartitioning(),
                               d2, i2).execute(0, TaskContext(0, 1)):
        pass
    assert os.path.exists(i2)


def test_invalidate_map_ids_subset(tmp_path):
    from blaze_tpu.parallel.shuffle import LocalShuffleManager, block_map_id

    mgr = LocalShuffleManager(str(tmp_path))
    for m_id in range(3):
        for p in mgr.map_output_paths(5, m_id):
            open(p, "wb").write(b"x")
    # partial: only map 1's pair goes
    assert mgr.invalidate(5, map_ids=[1]) == 2
    left = sorted(os.listdir(tmp_path))
    assert not any("_1." in f for f in left) and len(left) == 4
    # full: the rest
    assert mgr.invalidate(5) == 4
    assert os.listdir(tmp_path) == []
    # the block -> producing-map-id attribution the reader relies on
    data, _ = mgr.map_output_paths(7, 2)
    assert block_map_id((data, 0, 10)) == 2
    assert block_map_id(b"inmemory") is None
    assert block_map_id(("/odd/name.bin", 0, 1)) is None
