"""Spark plan interception layer: catalyst toJSON parsing, expression
conversion, convert strategy (trial conversion + fallback +
inefficient-convert removal), end-to-end execution of converted plans.

≙ the reference's JVM-side conversion stack
(BlazeConvertStrategy.scala, BlazeConverters.scala,
NativeConverters.scala) exercised the way its TPC-DS differential
suite exercises converted plans — here against in-memory oracles.
"""

import json

import numpy as np
import pytest

from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.spark import (
    BlazeSparkSession, ConversionContext, ConvertTag, UnsupportedSparkExpr,
    apply_strategy, convert_expr, convert_spark_plan, parse_plan_json,
)
from blaze_tpu.spark.plan_json import _parse_tree

import spark_fixtures as F


def parse_expr(tree):
    return _parse_tree(F.flatten(tree))


# ------------------------------------------------------------ plan parsing

def test_parse_plan_json_rebuilds_tree():
    plan = F.filter_(
        F.binop("GreaterThan", F.attr("x", 1), F.lit(5, "long")),
        F.scan("t", [F.attr("x", 1)]),
    )
    root = parse_plan_json(json.dumps(F.flatten(plan)))
    assert root.name == "FilterExec"
    assert root.child(0).name == "FileSourceScanExec"
    cond = root.expr("condition")
    assert cond.name == "GreaterThan"
    assert cond.child(0).name == "AttributeReference"


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        parse_plan_json(json.dumps([{"class": "X", "num-children": 2}]))


# ------------------------------------------------------- expr conversion

def test_convert_exprs_basic():
    e = convert_expr(parse_expr(
        F.binop("Add", F.attr("x", 1), F.lit(3, "long"))
    ))
    from blaze_tpu.exprs.ir import BinOp, Col, Lit

    assert isinstance(e, BinOp) and e.op == "+"
    assert isinstance(e.left, Col) and e.left.name == "#1"
    assert isinstance(e.right, Lit) and e.right.value == 3


def test_convert_case_when_reconstructs_branches():
    # CaseWhen serializes branches as tuples catalyst degrades to null;
    # the converter rebuilds from child arity (with and without else)
    cw_else = F.T(
        F.X + "CaseWhen",
        [
            F.binop("LessThan", F.attr("x", 1), F.lit(0, "long")),
            F.lit(-1, "long"),
            F.lit(1, "long"),
        ],
    )
    from blaze_tpu.exprs.ir import Case

    e = convert_expr(parse_expr(cw_else))
    assert isinstance(e, Case) and len(e.branches) == 1 and e.else_ is not None
    cw_no_else = F.T(
        F.X + "CaseWhen",
        [
            F.binop("LessThan", F.attr("x", 1), F.lit(0, "long")),
            F.lit(-1, "long"),
            F.binop("GreaterThan", F.attr("x", 1), F.lit(10, "long")),
            F.lit(10, "long"),
        ],
    )
    e = convert_expr(parse_expr(cw_no_else))
    assert isinstance(e, Case) and len(e.branches) == 2 and e.else_ is None


def test_convert_cast_and_try_cast():
    from blaze_tpu.exprs.ir import Cast

    e = convert_expr(parse_expr(F.cast(F.attr("x", 1), "integer")))
    assert isinstance(e, Cast) and e.to.kind.name == "INT32"
    t = parse_expr(F.T(F.X + "TryCast", [F.attr("x", 1)], dataType="decimal(10,2)"))
    e = convert_expr(t)
    assert isinstance(e, Cast) and e.to.is_decimal


def test_convert_function_classes():
    from blaze_tpu.exprs.ir import ScalarFunc

    e = convert_expr(parse_expr(F.un("Year", F.attr("d", 2, "date"))))
    assert isinstance(e, ScalarFunc) and e.name == "year"
    e = convert_expr(parse_expr(
        F.T(F.X + "Substring", [F.attr("s", 3, "string"), F.lit(1, "integer"), F.lit(2, "integer")])
    ))
    assert isinstance(e, ScalarFunc) and e.name == "substring" and len(e.args) == 3


def test_unknown_expr_raises():
    with pytest.raises(UnsupportedSparkExpr):
        convert_expr(parse_expr(F.T(F.X + "MadeUpExpr", [F.attr("x", 1)])))


# ---------------------------------------------------- end-to-end execution

LINEITEM_SCHEMA = Schema([
    Field("l_quantity", DataType.int64()),
    Field("l_extendedprice", DataType.int64()),
    Field("l_discount", DataType.int64()),
])


def make_session(n_rows=400, partitions=3):
    rng = np.random.RandomState(7)
    data = {
        "l_quantity": [int(v) for v in rng.randint(1, 50, n_rows)],
        "l_extendedprice": [int(v) for v in rng.randint(100, 10000, n_rows)],
        "l_discount": [int(v) for v in rng.randint(0, 10, n_rows)],
    }
    sess = BlazeSparkSession()
    sess.register_table("lineitem", data, LINEITEM_SCHEMA, partitions=partitions)
    return sess, data


def q6_like_plan():
    """scan -> filter -> project -> partial agg -> exchange(single) ->
    final agg, the canonical two-stage global aggregation."""
    s = F.scan(
        "lineitem",
        [F.attr("l_quantity", 1), F.attr("l_extendedprice", 2), F.attr("l_discount", 3)],
    )
    f = F.filter_(
        F.binop(
            "And",
            F.binop("LessThan", F.attr("l_quantity", 1), F.lit(24, "long")),
            F.binop("GreaterThanOrEqual", F.attr("l_discount", 3), F.lit(5, "long")),
        ),
        s,
    )
    pr = F.project(
        [F.alias(F.binop("Multiply", F.attr("l_extendedprice", 2), F.attr("l_discount", 3)), "rev", 10)],
        f,
    )
    partial = F.hash_agg([], [F.agg_expr(F.sum_(F.attr("rev", 10)), "Partial", 20)], pr)
    ex = F.shuffle(F.single_partition(), partial)
    final = F.hash_agg(
        [],
        [F.agg_expr(F.sum_(F.attr("rev", 10)), "Final", 20)],
        ex,
        result=[F.alias(F.attr("sum(rev)", 20), "revenue", 21)],
    )
    return F.wscg(final)


def test_q6_like_plan_end_to_end():
    sess, data = make_session()
    out = sess.execute(F.flatten(q6_like_plan()))
    expected = sum(
        p * d
        for q, p, d in zip(data["l_quantity"], data["l_extendedprice"], data["l_discount"])
        if q < 24 and d >= 5
    )
    assert list(out.keys()) == ["revenue"]
    assert out["revenue"] == [expected]


def test_group_by_plan_with_hash_exchange():
    """scan -> partial group-agg -> hash exchange -> final -> sort."""
    s = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_discount", 3)])
    partial = F.hash_agg(
        [F.attr("l_discount", 3)],
        [
            F.agg_expr(F.sum_(F.attr("l_quantity", 1)), "Partial", 20),
            F.agg_expr(F.count(), "Partial", 21),
        ],
        s,
    )
    ex = F.shuffle(F.hash_partitioning([F.attr("l_discount", 3)], 4), partial)
    final = F.hash_agg(
        [F.attr("l_discount", 3)],
        [
            F.agg_expr(F.sum_(F.attr("l_quantity", 1)), "Final", 20),
            F.agg_expr(F.count(), "Final", 21),
        ],
        ex,
        result=[
            F.attr("l_discount", 3),
            F.alias(F.attr("sum", 20), "total_qty", 30),
            F.alias(F.attr("cnt", 21), "n", 31),
        ],
    )
    sess, data = make_session()
    out = sess.execute(F.flatten(final))
    exp = {}
    for q, d in zip(data["l_quantity"], data["l_discount"]):
        t = exp.setdefault(d, [0, 0])
        t[0] += q
        t[1] += 1
    got = {
        d: (s, n)
        for d, s, n in zip(out["l_discount"], out["total_qty"], out["n"])
    }
    assert got == {d: (s, n) for d, (s, n) in exp.items()}


def test_broadcast_join_plan():
    """BHJ: dim table broadcast-joined to fact table."""
    sess, data = make_session()
    dim_schema = Schema([
        Field("d_key", DataType.int64()),
        Field("d_name", DataType.string(16)),
    ])
    sess.register_table(
        "dim",
        {"d_key": list(range(10)), "d_name": [f"name{i}" for i in range(10)]},
        dim_schema,
    )
    fact = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_discount", 3)])
    dim = F.broadcast(F.scan("dim", [F.attr("d_key", 5), F.attr("d_name", 6)]))
    join = F.bhj(
        [F.attr("l_discount", 3)], [F.attr("d_key", 5)],
        "Inner", "right", fact, dim,
    )
    pr = F.project(
        [F.attr("l_quantity", 1), F.attr("d_name", 6)],
        join,
    )
    out = sess.execute(F.flatten(pr))
    # every discount value 0..9 matches dim key
    assert len(out["l_quantity"]) == len(data["l_quantity"])
    for q, name in zip(out["l_quantity"], out["d_name"]):
        assert name.startswith("name")


def test_take_ordered_and_project():
    sess, data = make_session()
    s = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_extendedprice", 2)])
    plan = F.take_ordered(
        5,
        [F.sort_order(F.attr("l_extendedprice", 2), asc=False)],
        [F.attr("l_quantity", 1), F.attr("l_extendedprice", 2)],
        s,
    )
    out = sess.execute(F.flatten(plan))
    exp = sorted(data["l_extendedprice"], reverse=True)[:5]
    assert out["l_extendedprice"] == exp


# ---------------------------------------------------------- task defs

def test_task_definitions_roundtrip():
    """Converted plan -> stage split at exchanges -> per-task
    TaskDefinition bytes -> scheduler execution over shuffle files
    matches the in-process run (the NativeRDD + DAGScheduler contract
    end-to-end over the serde boundary)."""
    sess, data = make_session()
    plan_json = F.flatten(q6_like_plan())
    expected = sess.execute(plan_json)

    stages = sess.task_definitions(plan_json)
    assert len(stages) == 2  # map stage + result stage
    assert len(stages[0]) == 3  # one map task per input partition
    got = sess.execute_distributed(plan_json)
    assert got == expected


def test_distributed_group_by_matches_inprocess():
    sess, data = make_session()
    s = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_discount", 3)])
    partial = F.hash_agg(
        [F.attr("l_discount", 3)],
        [F.agg_expr(F.sum_(F.attr("l_quantity", 1)), "Partial", 20)],
        s,
    )
    ex = F.shuffle(F.hash_partitioning([F.attr("l_discount", 3)], 4), partial)
    final = F.hash_agg(
        [F.attr("l_discount", 3)],
        [F.agg_expr(F.sum_(F.attr("l_quantity", 1)), "Final", 20)],
        ex,
        result=[
            F.attr("l_discount", 3),
            F.alias(F.attr("sum", 20), "total_qty", 30),
        ],
    )
    plan_json = F.flatten(final)
    a = sess.execute(plan_json)
    b = sess.execute_distributed(plan_json)
    assert dict(zip(a["l_discount"], a["total_qty"])) == dict(
        zip(b["l_discount"], b["total_qty"])
    )


# ------------------------------------------------------------- strategy

def test_strategy_tags_and_fallback():
    sess, data = make_session()
    # plan with an unconvertible exec in the middle
    s = F.scan("lineitem", [F.attr("l_quantity", 1)])
    weird = F.T(F.P + "MadeUpExec", [s])
    f = F.filter_(
        F.binop("LessThan", F.attr("l_quantity", 1), F.lit(10, "long")), weird
    )
    node = parse_plan_json(json.dumps(F.flatten(f)))
    ctx = ConversionContext(catalog=sess.catalog)
    tags = apply_strategy(node, ctx)
    # filter itself is convertible but MadeUp falls back; without a
    # host_fallback the conversion of MadeUp raises inside apply (tag NEVER)
    by_name = {}
    def walk(n):
        by_name.setdefault(n.name, tags.get(id(n)))
        for c in n.children:
            walk(c)
    walk(node)
    assert by_name["MadeUpExec"] == ConvertTag.NEVER


def test_strategy_host_fallback_executes():
    """Unconvertible subtree runs through the registered host fallback
    (the ConvertToNative / resourcesMap seam) and the convertible
    parent consumes its output natively."""
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.batch import batch_from_pydict

    schema = Schema([Field("#1", DataType.int64())])

    def fallback(node):
        # the "JVM" executes the subtree and stages the result
        return MemoryScanExec(
            [[batch_from_pydict({"#1": [1, 5, 20, 30]}, schema)]], schema
        )

    sess = BlazeSparkSession(host_fallback=fallback)
    weird = F.T(F.P + "MadeUpExec", [])
    f = F.filter_(F.binop("GreaterThan", F.attr("x", 1), F.lit(4, "long")), weird)
    out = sess.execute(F.flatten(f))
    assert out["#1"] == [5, 20, 30]


def test_inefficient_convert_removed():
    """A cheap native Filter sandwiched between non-native parent and
    non-native child re-tags NeverConvert (≙ removeInefficientConverts,
    BlazeConvertStrategy.scala:182-243)."""
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.batch import batch_from_pydict

    schema = Schema([Field("#1", DataType.int64())])
    fallback_calls = []

    def fallback(node):
        fallback_calls.append(node.name)
        return MemoryScanExec(
            [[batch_from_pydict({"#1": [1, 5]}, schema)]], schema
        )

    inner = F.T(F.P + "MadeUpExec", [])
    filt = F.filter_(F.binop("GreaterThan", F.attr("x", 1), F.lit(0, "long")), inner)
    outer = F.T(F.P + "MadeUpOuterExec", [filt])
    node = parse_plan_json(json.dumps(F.flatten(outer)))
    ctx = ConversionContext(catalog={}, host_fallback=fallback)
    plan = convert_spark_plan(node, ctx, rename_root=False)
    # after fixpoint, the filter is part of the fallen-back subtree:
    # the final fallback call covers MadeUpOuterExec (whole sandwich)
    assert "MadeUpOuterExec" in fallback_calls


def test_scalar_subquery_evaluated_driver_side():
    """ScalarSubquery's embedded plan runs eagerly at conversion and
    its value enters the main plan as a typed literal
    (≙ SparkScalarSubqueryWrapperExpr, blaze.proto:10001)."""
    sess, data = make_session()
    # subquery: max(l_extendedprice) over the same table
    sub = F.hash_agg(
        [],
        [F.agg_expr(F.max_(F.attr("l_extendedprice", 2)), "Complete", 50)],
        F.scan("lineitem", [F.attr("l_extendedprice", 2)]),
        result=[F.alias(F.attr("mx", 50), "mx", 51)],
    )
    subquery = F.T(
        F.X + "ScalarSubquery",
        plan=F.flatten(sub),
        exprId=F.eid(60),
        dataType="long",
    )
    # main: rows where extendedprice == (select max(...))
    main = F.filter_(
        F.binop("EqualTo", F.attr("l_extendedprice", 2), subquery),
        F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_extendedprice", 2)]),
    )
    out = sess.execute(F.flatten(main))
    mx = max(data["l_extendedprice"])
    assert out["#2"] and all(v == mx for v in out["#2"])


def test_op_disable_flag_forces_fallback():
    from blaze_tpu import conf

    sess, data = make_session()
    s = F.scan("lineitem", [F.attr("l_quantity", 1)])
    f = F.filter_(
        F.binop("LessThan", F.attr("l_quantity", 1), F.lit(10, "long")), s
    )
    node = parse_plan_json(json.dumps(F.flatten(f)))
    ctx = ConversionContext(catalog=sess.catalog)
    conf.set_conf("spark.blaze.enable.filter", False)
    try:
        tags = apply_strategy(node, ctx)
        by_name = {}
        def walk(n):
            by_name[n.name] = tags.get(id(n))
            for c in n.children:
                walk(c)
        walk(node)
        assert by_name["FilterExec"] == ConvertTag.NEVER
    finally:
        conf.set_conf("spark.blaze.enable.filter", True)


def test_scheduler_task_retry_recovers():
    """A transiently failing task re-runs from a fresh TaskDefinition
    (≙ Spark task retry, the reference's only fault-recovery tier) and
    the query still matches the in-process result."""
    import blaze_tpu.runtime.scheduler as sched
    from blaze_tpu.runtime.scheduler import run_stages, split_stages
    from blaze_tpu.serde import from_proto
    from blaze_tpu.batch import batch_to_pydict

    sess, data = make_session()
    plan_json = F.flatten(q6_like_plan())
    expected = sess.execute(plan_json)

    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)

    real_run_task = from_proto.run_task
    fails = {"n": 2}  # fail the first two task attempts

    def flaky_run_task(td, **kw):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise RuntimeError("injected task failure")
        return real_run_task(td, **kw)

    from_proto.run_task = flaky_run_task
    # run_stages resolves run_task at call time through the module
    try:
        got = []
        for b in run_stages(stages, manager, max_task_attempts=3):
            got.extend(batch_to_pydict(b)["revenue"])
    finally:
        from_proto.run_task = real_run_task
    assert got == expected["revenue"]
    assert fails["n"] == 0  # failures actually happened


def test_scheduler_exhausted_retries_raise():
    from blaze_tpu.runtime.scheduler import run_stages, split_stages
    from blaze_tpu.serde import from_proto

    sess, data = make_session()
    plan = sess.plan(F.flatten(q6_like_plan()))
    stages, manager = split_stages(plan)
    real_run_task = from_proto.run_task
    from_proto.run_task = lambda td, **kw: (_ for _ in ()).throw(RuntimeError("boom"))
    try:
        with pytest.raises(RuntimeError):
            list(run_stages(stages, manager, max_task_attempts=2))
    finally:
        from_proto.run_task = real_run_task


def test_range_partitioning_plan_global_sort():
    """Spark RangePartitioning exchange + SortExec converts and yields
    a total order across partitions (≙ Spark global ORDER BY)."""
    sess, data = make_session()
    s = F.scan("lineitem", [F.attr("l_extendedprice", 2)])
    ex = F.shuffle(
        F.range_partitioning([F.sort_order(F.attr("l_extendedprice", 2))], 3), s
    )
    srt = F.sort([F.sort_order(F.attr("l_extendedprice", 2))], ex)
    out = sess.execute(F.flatten(srt))
    # the root-naming walk now steps through Sort/Exchange to the scan,
    # so the output carries the user-facing column name
    assert out["l_extendedprice"] == sorted(data["l_extendedprice"])


def test_generate_json_tuple_conversion():
    """Spark GenerateExec(JsonTuple) converts to the host json_tuple
    generator (≙ generate/json_tuple.rs via the UDTF seam)."""
    sess = BlazeSparkSession()
    schema = Schema([Field("j", DataType.string(64))])
    sess.register_table(
        "t", {"j": ['{"a":"1","b":"x"}', '{"a":"2"}', None]}, schema
    )
    s = F.scan("t", [F.attr("j", 1, "string")])
    g = F.T(
        F.P + "GenerateExec",
        [s],
        generator=F.flatten(F.T(
            F.X + "JsonTuple",
            [F.attr("j", 1, "string"), F.lit("a", "string"), F.lit("b", "string")],
        )),
        requiredChildOutput=[],
        outer=False,
        generatorOutput=[F.flatten(F.attr("a", 10, "string")),
                         F.flatten(F.attr("b", 11, "string"))],
    )
    out = sess.execute(F.flatten(g))
    assert out["#10"] == ["1", "2", None]
    assert out["#11"] == ["x", None, None]


# ------------------------------- expression-level UDF wrapper fallback

def test_unconvertible_expr_wraps_as_udf_not_subtree_fallback():
    """≙ NativeConverters.convertExpr:305/convertExprWithFallback:407:
    an unconvertible EXPRESSION (here a ScalaUDF) inside a projection
    or filter binds its convertible children as native params, ships
    the rebound catalyst subtree as the opaque blob, and the OPERATOR
    stays native — the session needs no host_fallback at all.  The
    evaluator (the JVM half) receives args over the Arrow C FFI and
    the blob it must deserialize; dropping the evaluator restores the
    per-subtree host fallback path."""
    import json

    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.gateway import export_batch_ffi, import_batch_ffi
    from blaze_tpu.runtime.scheduler import run_stages, split_stages
    from blaze_tpu.schema import Field as BField, Schema as BSchema
    from blaze_tpu.spark import udf_bridge
    from blaze_tpu.spark.expr_converter import UnsupportedSparkExpr

    sess, data = make_session()
    blobs = []

    def evaluate(serialized, args_addr, args_schema, out_dtype):
        # the "JVM": deserialize the rebound expression and interpret
        # it — the blob is the catalyst subtree with BoundReferences
        flat = json.loads(bytes(serialized).decode())
        blobs.append(flat)
        assert flat[0]["class"].endswith("ScalaUDF")
        brefs = [n for n in flat if n["class"].endswith("BoundReference")]
        assert sorted(n["ordinal"] for n in brefs) == list(
            range(len(args_schema.fields)))
        # the blob must type every param truthfully — a NullType
        # BoundReference would make a real JVM evaluate params as null
        assert all(n["dataType"] == "long" for n in brefs), brefs
        args = import_batch_ffi(args_addr, args_schema)
        d = batch_to_pydict(args)
        cols = [d[f.name] for f in args_schema.fields]
        out = [None if (a is None or b is None) else a * 2 + b
               for a, b in zip(*cols)]
        out_schema = BSchema([BField("__udf_out", out_dtype)])
        return export_batch_ffi(batch_from_pydict({"__udf_out": out}, out_schema))

    # first param is a COMPUTED subtree (Add dumps no dataType field:
    # the wrapper must derive the BoundReference type, not write null)
    udf = F.T(
        "org.apache.spark.sql.catalyst.expressions.ScalaUDF",
        [F.binop("Add", F.attr("l_quantity", 1), F.attr("l_discount", 3)),
         F.attr("l_discount", 3)],
        dataType="long", udfName="q2d",
    )
    s = F.scan("lineitem", [F.attr("l_quantity", 1),
                            F.attr("l_extendedprice", 2),
                            F.attr("l_discount", 3)])
    f = F.filter_(F.binop("GreaterThan", udf, F.lit(50, "long")), s)
    pr = F.project([F.alias(udf, "u", 10),
                    F.alias(F.attr("l_extendedprice", 2), "price", 11)], f)
    js = json.dumps([dict(x) for x in F.flatten(pr)])

    exp = [
        ((q + disc) * 2 + disc, p)
        for q, p, disc in zip(data["l_quantity"], data["l_extendedprice"],
                              data["l_discount"])
        if (q + disc) * 2 + disc > 50
    ]

    udf_bridge.register_udf_evaluator(evaluate)
    try:
        # no host_fallback: conversion would RAISE if the wrapper
        # didn't keep the operators native
        got = sess.execute(js)
        assert sorted(zip(got["u"], got["price"])) == sorted(exp)
        assert blobs, "evaluator never saw the serialized blob"

        # same plan across the serde + scheduler boundary (the blob
        # rides the TaskDefinition protobuf bit-for-bit)
        stages, manager = split_stages(sess.plan(js))
        got2 = {"u": [], "price": []}
        for b in run_stages(stages, manager):
            d = batch_to_pydict(b)
            got2["u"].extend(d["u"])
            got2["price"].extend(d["price"])
        assert sorted(zip(got2["u"], got2["price"])) == sorted(exp)
    finally:
        udf_bridge.register_udf_evaluator(None)

    # without the evaluator the wrapper is not emitted: the session
    # (which has no host_fallback) surfaces the strategy-layer
    # unconvertible error — the per-subtree fallback path as before
    from blaze_tpu.spark.converters import UnsupportedSparkExec

    with pytest.raises(UnsupportedSparkExec, match="unconvertible"):
        sess.plan(js)


def test_agg_filter_and_distinct_are_gated():
    """AggregateExpression FILTER (WHERE ...) and isDistinct must not
    silently drop — either gates to subtree fallback (wrong numbers
    otherwise).  The gate itself is pinned via _agg_function (the
    strategy layer genericizes the message before sess.plan sees it)."""
    from blaze_tpu.spark.converters import UnsupportedSparkExec, _agg_function
    from blaze_tpu.spark.plan_json import _parse_tree

    sess, data = make_session()

    def agg_expr_node(**agg_extra):
        ae = F.T(
            "org.apache.spark.sql.catalyst.expressions.aggregate.AggregateExpression",
            [F.sum_(F.attr("l_quantity", 1))],
            mode="Partial", resultId=F.eid(20), **agg_extra,
        )
        return _parse_tree([dict(x) for x in F.flatten(ae)])

    # gate-level: the specific messages
    with pytest.raises(UnsupportedSparkExec, match="distinct aggregate"):
        _agg_function(agg_expr_node(isDistinct=True))
    with pytest.raises(UnsupportedSparkExec, match="FILTER clause"):
        _agg_function(agg_expr_node(filter=[dict(x) for x in F.flatten(
            F.binop("GreaterThan", F.attr("l_quantity", 1), F.lit(5, "long")))]))
    # plain agg converts
    assert _agg_function(agg_expr_node()).fn == "sum"

    def agg_plan(**agg_extra):
        s = F.scan("lineitem", [F.attr("l_quantity", 1)])
        ae = F.T(
            "org.apache.spark.sql.catalyst.expressions.aggregate.AggregateExpression",
            [F.sum_(F.attr("l_quantity", 1))],
            mode="Partial", resultId=F.eid(20), **agg_extra,
        )
        partial = F.T(
            "org.apache.spark.sql.execution.aggregate.HashAggregateExec",
            [s], groupingExpressions=[], aggregateExpressions=[[dict(x) for x in F.flatten(ae)]],
            resultExpressions=[],
        )
        return json.dumps([dict(x) for x in F.flatten(partial)])

    with pytest.raises(UnsupportedSparkExec, match="distinct|unconvertible"):
        sess.plan(agg_plan(isDistinct=True))
    with pytest.raises(UnsupportedSparkExec, match="FILTER|unconvertible"):
        sess.plan(agg_plan(
            isDistinct=False,
            filter=[dict(x) for x in F.flatten(
                F.binop("GreaterThan", F.attr("l_quantity", 1), F.lit(5, "long")))],
        ))
