"""Spark-serialized-expression UDF wrapper: the wire seam.

≙ reference ``SparkUDFWrapperContext.scala:37-96`` +
``spark_udf_wrapper.rs:45-229``: the engine carries the JVM-serialized
Spark expression as OPAQUE bytes through the plan protobuf; at eval
the bound argument batch crosses the Arrow C FFI to the JVM context
and the result array crosses back.  No JVM runs in this image, so the
tests install a stand-in evaluator at the same seam and assert:

- the proto round trip preserves the serialized blob bit-for-bit
- evaluation ships args/results through the REAL Arrow C FFI path
  (gateway export/import — the C structs, not a python shortcut)
- a TaskDefinition containing the wrapper decodes and executes
- with no evaluator installed, decode still succeeds (wire compat)
  and evaluation raises the documented error
"""

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.exprs.ir import SparkUdfWrapper
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.spark import udf_bridge

# a stand-in for JavaSerializer output: opaque, non-UTF8, with NULs
FAKE_SERIALIZED = bytes(range(256)) + b"\xac\xed\x00\x05sr\x00"

SCHEMA = Schema([Field("x", DataType.int64()), Field("y", DataType.int64())])


def _plan():
    data = {"x": [1, 2, None, 4, 5], "y": [10, 20, 30, 40, 50]}
    scan = MemoryScanExec([[batch_from_pydict(data, SCHEMA)]], SCHEMA)
    udf = SparkUdfWrapper(
        FAKE_SERIALIZED, [col("x"), col("y")], DataType.int64(),
        "jvmexpr(x + y)",
    )
    from blaze_tpu.exprs.ir import Alias

    return ProjectExec(scan, [col("x"), Alias(udf, "z")])


def _install_add_evaluator(seen):
    """Evaluator standing where the JVM would: receives the serialized
    blob + the args as an exported Arrow C array, computes x + y, and
    returns the result through another FFI export."""
    from blaze_tpu.gateway import export_batch_ffi, import_batch_ffi

    def evaluate(serialized, args_addr, args_schema, out_dtype):
        seen.append(bytes(serialized))
        args = import_batch_ffi(args_addr, args_schema)
        d = batch_to_pydict(args)
        # positional args, like the JVM context binds them
        xs, ys = (d[f.name] for f in args_schema.fields)
        out = [
            None if (a is None or b is None) else a + b
            for a, b in zip(xs, ys)
        ]
        out_schema = Schema([Field("__udf_out", out_dtype)])
        return export_batch_ffi(
            batch_from_pydict({"__udf_out": out}, out_schema)
        )

    udf_bridge.register_udf_evaluator(evaluate)


def _run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def test_wrapper_proto_roundtrip_preserves_blob():
    from blaze_tpu.serde.from_proto import expr_from_proto
    from blaze_tpu.serde.to_proto import expr_to_proto

    udf = SparkUdfWrapper(FAKE_SERIALIZED, [col("x")], DataType.int64(), "f(x)")
    back = expr_from_proto(expr_to_proto(udf))
    assert isinstance(back, SparkUdfWrapper)
    assert back.serialized == FAKE_SERIALIZED  # bit-for-bit
    assert back.expr_string == "f(x)"
    assert back.dtype == DataType.int64()
    assert [a.name for a in back.args] == ["x"]


def test_wrapper_eval_crosses_arrow_ffi():
    seen = []
    _install_add_evaluator(seen)
    try:
        got = _run(_plan())
    finally:
        udf_bridge.register_udf_evaluator(None)
    assert got["z"] == [11, 22, None, 44, 55]
    assert seen == [FAKE_SERIALIZED]  # blob reached the "JVM" untouched


def test_wrapper_through_task_definition():
    """The wrapper crosses the TaskDefinition protobuf boundary and
    executes on the decoded plan (the full gateway task path)."""
    from blaze_tpu.serde.from_proto import run_task
    from blaze_tpu.serde.to_proto import task_definition

    seen = []
    _install_add_evaluator(seen)
    try:
        td = task_definition(_plan(), "udf_wire", 0, 0)
        rows = {"x": [], "z": []}
        for b in run_task(td):
            d = batch_to_pydict(b)
            rows["x"].extend(d["x"])
            rows["z"].extend(d["z"])
    finally:
        udf_bridge.register_udf_evaluator(None)
    assert rows["z"] == [11, 22, None, 44, 55]
    assert seen == [FAKE_SERIALIZED]


def test_wrapper_without_evaluator_decodes_but_refuses_eval():
    from blaze_tpu.serde.from_proto import run_task
    from blaze_tpu.serde.to_proto import task_definition

    td = task_definition(_plan(), "udf_wire2", 0, 0)  # decode-compatible
    with pytest.raises(RuntimeError, match="registered evaluator"):
        for _ in run_task(td):
            pass


def test_wrapper_nested_inside_wrapper_arg():
    """A wrapper whose ARG is another wrapper (host subtree inside an
    arg expr): both hoist through the split machinery and evaluate
    through the FFI in dependency order."""
    from blaze_tpu.batch import batch_from_pydict as bfp
    from blaze_tpu.exprs.ir import Alias
    from blaze_tpu.gateway import export_batch_ffi, import_batch_ffi

    def evaluate(serialized, args_addr, args_schema, out_dtype):
        args = import_batch_ffi(args_addr, args_schema)
        d = batch_to_pydict(args)
        cols = [d[f.name] for f in args_schema.fields]
        out = [
            None if any(v is None for v in row) else sum(row) + 1
            for row in zip(*cols)
        ]
        out_schema = Schema([Field("__udf_out", out_dtype)])
        return export_batch_ffi(bfp({"__udf_out": out}, out_schema))

    udf_bridge.register_udf_evaluator(evaluate)
    try:
        data = {"x": [1, 2, None], "y": [10, 20, 30]}
        scan = MemoryScanExec([[batch_from_pydict(data, SCHEMA)]], SCHEMA)
        inner = SparkUdfWrapper(b"inner", [col("x"), col("y")],
                                DataType.int64(), "inner(x,y)")
        outer = SparkUdfWrapper(b"outer", [inner], DataType.int64(),
                                "outer(inner)")
        plan = ProjectExec(scan, [Alias(outer, "z")])
        got = _run(plan)
    finally:
        udf_bridge.register_udf_evaluator(None)
    # inner = x+y+1; outer = inner+1
    assert got["z"] == [13, 24, None]
