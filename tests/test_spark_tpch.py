"""TPC-H differentials THROUGH the Spark interception layer.

The existing tpch suite executes hand-built ExecNode trees; here the
same queries are expressed as catalyst ``toJSON`` physical-plan dumps,
cross ``spark/converters.py`` (strategy + expression conversion), run
via BOTH the in-process collect path and the stage scheduler (every
task crossing the TaskDefinition protobuf boundary), and are validated
against the same independent numpy oracles — the shape of the
reference's differential gate, which always runs full conversion
(``.github/workflows/tpcds-reusable.yml:83-143``).
"""

import numpy as np
import pytest

from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.spark import BlazeSparkSession
from blaze_tpu.tpch import TPCH_SCHEMAS
from blaze_tpu.tpch import oracle as O
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

import spark_fixtures as F

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 2

# stable exprId blocks per table (column order = TPCH_SCHEMAS order)
_BASE = {"lineitem": 0, "orders": 20, "customer": 40, "part": 60}
_DTYPES = {}
_IDS = {}
for _t, _b in _BASE.items():
    for _i, _f in enumerate(TPCH_SCHEMAS[_t].fields):
        _IDS[_f.name] = _b + _i + 1
        dt = _f.dtype
        if dt.is_decimal:
            _DTYPES[_f.name] = f"decimal({dt.precision},{dt.scale})"
        elif dt.is_string:
            _DTYPES[_f.name] = "string"
        elif dt.kind.name == "DATE32":
            _DTYPES[_f.name] = "date"
        elif dt.kind.name == "INT32":
            _DTYPES[_f.name] = "integer"
        else:
            _DTYPES[_f.name] = "long"


def a(name: str) -> dict:
    """AttributeReference for a base-table column."""
    return F.attr(name, _IDS[name], _DTYPES[name])


def ar(name: str, i: int, dtype: str = "long") -> dict:
    return F.attr(name, i, dtype)


def dec(v) -> dict:
    return F.lit(str(v), "decimal(12,2)")


def date(s: str) -> dict:
    return F.lit(s, "date")


def and_(*es):
    out = es[0]
    for e in es[1:]:
        out = F.binop("And", out, e)
    return out


def or_(*es):
    out = es[0]
    for e in es[1:]:
        out = F.binop("Or", out, e)
    return out


def in_(child, *vals):
    return F.T(F.X + "In", [child] + [F.lit(v, "string") for v in vals])


def two_stage(groupings, aggs_fns, child, n_parts, result=None):
    """(partial agg -> hash/single exchange -> final agg) with stable
    resultIds, the canonical catalyst split."""
    partial = F.hash_agg(
        groupings,
        [F.agg_expr(fn, "Partial", rid) for fn, rid in aggs_fns],
        child,
    )
    part = (
        F.hash_partitioning(groupings, n_parts)
        if groupings
        else F.single_partition()
    )
    ex = F.shuffle(part, partial)
    return F.hash_agg(
        groupings,
        [F.agg_expr(fn, "Final", rid) for fn, rid in aggs_fns],
        ex,
        result=result,
    )


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def sess(data):
    s = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in TPCH_SCHEMAS:
        s.register_table(
            name,
            MemoryScanExec(
                table_to_batches(data[name], TPCH_SCHEMAS[name], N_PARTS, batch_rows=4096),
                TPCH_SCHEMAS[name],
            ),
        )
    return s


# ------------------------------------------------------------------- plans

def q6_plan():
    scan = F.scan(
        "lineitem",
        [a("l_quantity"), a("l_extendedprice"), a("l_discount"), a("l_shipdate")],
    )
    f = F.filter_(
        and_(
            F.binop("GreaterThanOrEqual", a("l_shipdate"), date("1994-01-01")),
            F.binop("LessThan", a("l_shipdate"), date("1995-01-01")),
            F.binop("GreaterThanOrEqual", a("l_discount"), dec("0.05")),
            F.binop("LessThanOrEqual", a("l_discount"), dec("0.07")),
            F.binop("LessThan", a("l_quantity"), dec("24")),
        ),
        F.wscg(scan),
    )
    rev = F.binop("Multiply", a("l_extendedprice"), a("l_discount"))
    proj = F.project([F.alias(rev, "rev", 101)], f)
    return two_stage(
        [],
        [(F.sum_(ar("rev", 101, "decimal(12,2)")), 201)],
        proj,
        N_PARTS,
        result=[F.alias(ar("sum(rev)", 201, "decimal(22,2)"), "revenue", 301)],
    )


def q1_plan():
    scan = F.scan(
        "lineitem",
        [a("l_quantity"), a("l_extendedprice"), a("l_discount"), a("l_tax"),
         a("l_returnflag"), a("l_linestatus"), a("l_shipdate")],
    )
    f = F.filter_(
        F.binop("LessThanOrEqual", a("l_shipdate"), date("1998-09-02")), scan
    )
    one = dec("1")
    disc_price = F.binop(
        "Multiply", a("l_extendedprice"), F.binop("Subtract", one, a("l_discount"))
    )
    charge = F.binop(
        "Multiply",
        F.binop("Multiply", a("l_extendedprice"), F.binop("Subtract", one, a("l_discount"))),
        F.binop("Add", one, a("l_tax")),
    )
    proj = F.project(
        [a("l_returnflag"), a("l_linestatus"), a("l_quantity"),
         a("l_extendedprice"), a("l_discount"),
         F.alias(disc_price, "disc_price", 101), F.alias(charge, "charge", 102)],
        f,
    )
    groupings = [a("l_returnflag"), a("l_linestatus")]
    aggs = [
        (F.sum_(a("l_quantity")), 201),
        (F.sum_(a("l_extendedprice")), 202),
        (F.sum_(ar("disc_price", 101, "decimal(16,4)")), 203),
        (F.sum_(ar("charge", 102, "decimal(20,6)")), 204),
        (F.avg(a("l_quantity")), 205),
        (F.avg(a("l_extendedprice")), 206),
        (F.avg(a("l_discount")), 207),
        (F.count(), 208),
    ]
    agg = two_stage(groupings, aggs, proj, N_PARTS)
    sorted_ = F.sort(
        [F.sort_order(a("l_returnflag")), F.sort_order(a("l_linestatus"))],
        F.shuffle(F.single_partition(), agg),
    )
    names = [
        ("l_returnflag", _IDS["l_returnflag"], "string"),
        ("l_linestatus", _IDS["l_linestatus"], "string"),
        ("sum_qty", 201, "decimal(22,2)"),
        ("sum_base_price", 202, "decimal(22,2)"),
        ("sum_disc_price", 203, "decimal(26,4)"),
        ("sum_charge", 204, "decimal(30,6)"),
        ("avg_qty", 205, "decimal(16,6)"),
        ("avg_price", 206, "decimal(16,6)"),
        ("avg_disc", 207, "decimal(16,6)"),
        ("count_order", 208, "long"),
    ]
    return F.project(
        [F.alias(ar(n, rid, dt), n, 300 + i) for i, (n, rid, dt) in enumerate(names)],
        sorted_,
    )


def q3_plan():
    cust = F.project(
        [a("c_custkey")],
        F.filter_(
            F.binop("EqualTo", a("c_mktsegment"), F.lit("BUILDING", "string")),
            F.scan("customer", [a("c_custkey"), a("c_mktsegment")]),
        ),
    )
    orders = F.project(
        [a("o_orderkey"), a("o_custkey"), a("o_orderdate"), a("o_shippriority")],
        F.filter_(
            F.binop("LessThan", a("o_orderdate"), date("1995-03-15")),
            F.scan("orders", [a("o_orderkey"), a("o_custkey"),
                              a("o_orderdate"), a("o_shippriority")]),
        ),
    )
    co = F.bhj(
        [a("c_custkey")], [a("o_custkey")], "Inner", "left",
        F.broadcast(cust), orders,
    )
    line = F.project(
        [a("l_orderkey"),
         F.alias(
             F.binop("Multiply", a("l_extendedprice"),
                     F.binop("Subtract", dec("1"), a("l_discount"))),
             "rev", 110,
         )],
        F.filter_(
            F.binop("GreaterThan", a("l_shipdate"), date("1995-03-15")),
            F.scan("lineitem", [a("l_orderkey"), a("l_extendedprice"),
                                a("l_discount"), a("l_shipdate")]),
        ),
    )
    j = F.shj(
        [a("o_orderkey")], [a("l_orderkey")], "Inner", "left",
        F.shuffle(F.hash_partitioning([a("o_orderkey")], N_PARTS), co),
        F.shuffle(F.hash_partitioning([a("l_orderkey")], N_PARTS), line),
    )
    groupings = [a("o_orderkey"), a("o_orderdate"), a("o_shippriority")]
    agg = two_stage(
        groupings,
        [(F.sum_(ar("rev", 110, "decimal(16,4)")), 210)],
        j,
        N_PARTS,
    )
    return F.take_ordered(
        10,
        [F.sort_order(ar("revenue", 210, "decimal(26,4)"), asc=False),
         F.sort_order(a("o_orderdate"))],
        [F.alias(a("o_orderkey"), "l_orderkey", 320),
         F.alias(ar("revenue", 210, "decimal(26,4)"), "revenue", 321),
         F.alias(a("o_orderdate"), "o_orderdate", 322),
         F.alias(a("o_shippriority"), "o_shippriority", 323)],
        agg,
    )


def q19_plan():
    """q19 with the OR-of-ANDs as the BHJ's residual join condition —
    the inner-join residual path (post-join filter rewrite)."""
    line = F.project(
        [a("l_partkey"), a("l_quantity"),
         F.alias(
             F.binop("Multiply", a("l_extendedprice"),
                     F.binop("Subtract", dec("1"), a("l_discount"))),
             "rev", 111,
         )],
        F.filter_(
            and_(
                in_(a("l_shipmode"), "AIR", "REG AIR"),
                F.binop("EqualTo", a("l_shipinstruct"),
                        F.lit("DELIVER IN PERSON", "string")),
            ),
            F.scan("lineitem", [a("l_partkey"), a("l_quantity"),
                                a("l_extendedprice"), a("l_discount"),
                                a("l_shipinstruct"), a("l_shipmode")]),
        ),
    )
    part = F.scan("part", [a("p_partkey"), a("p_brand"),
                           a("p_size"), a("p_container")])
    qty = a("l_quantity")

    def branch(brand, containers, qlo, qhi, smax):
        return and_(
            F.binop("EqualTo", a("p_brand"), F.lit(brand, "string")),
            in_(a("p_container"), *containers),
            F.binop("GreaterThanOrEqual", qty, dec(qlo)),
            F.binop("LessThanOrEqual", qty, dec(qhi)),
            F.binop("GreaterThanOrEqual", a("p_size"), F.lit(1, "integer")),
            F.binop("LessThanOrEqual", a("p_size"), F.lit(smax, "integer")),
        )

    cond = or_(
        branch("Brand#12", ("SM CASE", "SM BOX", "SM PACK", "SM PKG"), 1, 11, 5),
        branch("Brand#23", ("MED BAG", "MED BOX", "MED PKG", "MED PACK"), 10, 20, 10),
        branch("Brand#34", ("LG CASE", "LG BOX", "LG PACK", "LG PKG"), 20, 30, 15),
    )
    j = F.bhj(
        [a("p_partkey")], [a("l_partkey")], "Inner", "left",
        F.broadcast(part), line, condition=cond,
    )
    proj = F.project([ar("rev", 111, "decimal(16,4)")], j)
    return two_stage(
        [],
        [(F.sum_(ar("rev", 111, "decimal(16,4)")), 211)],
        proj,
        N_PARTS,
        result=[F.alias(ar("sum(rev)", 211, "decimal(26,4)"), "revenue", 311)],
    )


# ------------------------------------------------------------------- tests

def _execute_both(sess, plan):
    """In-process collect AND the stage scheduler (TaskDefinition
    protobuf boundary + shuffle files) must agree."""
    import json

    js = json.dumps(F.flatten(plan))
    got = sess.execute(js)
    got_sched = sess.execute_distributed(js)
    rows = sorted(zip(*got.values())) if got else []
    rows_sched = sorted(zip(*got_sched.values())) if got_sched else []
    assert rows == rows_sched, "in-process vs scheduler mismatch"
    return got


def test_spark_q6(sess, data):
    got = _execute_both(sess, q6_plan())
    assert got["revenue"] == [O.oracle_q6(data)]


def test_spark_q1(sess, data):
    got = _execute_both(sess, q1_plan())
    exp = O.oracle_q1(data)
    keys = list(zip(got["l_returnflag"], got["l_linestatus"]))
    assert keys == sorted(keys)
    assert set(keys) == set(exp)
    for i, k in enumerate(keys):
        e = exp[k]
        for m in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
                  "count_order"):
            assert got[m][i] == e[m], (k, m)
        for m in ("avg_qty", "avg_price", "avg_disc"):
            assert abs(got[m][i] - e[m]) <= 1, (k, m)


def test_spark_q3(sess, data):
    got = _execute_both(sess, q3_plan())
    exp = O.oracle_q3(data)
    rows = list(zip(got["l_orderkey"], got["revenue"],
                    got["o_orderdate"], got["o_shippriority"]))
    assert len(rows) == len(exp)
    assert set((r[0], r[1]) for r in rows) == set((r[0], r[1]) for r in exp)
    assert [r[1] for r in rows] == sorted([r[1] for r in rows], reverse=True)


def test_vendored_spark351_q6_dump(sess, data):
    """A q6 plan dump in Spark 3.5.1's exact ``executedPlan.toJSON``
    encoding (child-INDEX fields like ``"child": 0`` / ``"left": 0``,
    case-object products for modes/origins/eval modes, struct-JSON
    requiredSchema, ColumnarToRow + InputAdapter wrappers, isnotnull
    guards, Cast-wrapped literals with timeZoneId, date literals as
    days-since-epoch strings) — the parser/converters must digest the
    real serialization shape, not just tests' builder emulation."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "spark351_q6_plan.json")
    with open(path) as f:
        js = f.read()
    # sanity: the dump really uses the real-Spark encodings
    raw = json.loads(js)
    assert '"mode":{"product-class"' in js.replace(" ", "")
    assert any(n.get("child") == 0 for n in raw)
    assert '"evalMode"' in js and '"timeZoneId"' in js
    got = sess.execute(js)
    assert got["revenue"] == [O.oracle_q6(data)]


def test_spark_q19(sess, data):
    got = _execute_both(sess, q19_plan())
    exp = O.oracle_q19(data)
    assert len(got["revenue"]) == 1
    v = got["revenue"][0]
    if exp == 0:
        assert v is None or v == 0
    else:
        assert v == exp


# --------------------------------------------------- TPC-DS via conversion

def test_spark_tpcds_q3_star_join():
    """A TPC-DS star join (q3: date x item x store_sales, grouped brand
    revenue) through the catalyst toJSON converter — the TPC-DS side of
    the full-conversion differential gate."""
    import json

    from blaze_tpu.ops import MemoryScanExec as MS
    from blaze_tpu.spark import BlazeSparkSession
    from blaze_tpu.tpcds import TPCDS_SCHEMAS
    from blaze_tpu.tpcds.datagen import generate_all as ds_generate_all
    from blaze_tpu.tpcds.datagen import table_to_batches as ds_batches

    ds = ds_generate_all(0.002)
    sess = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in ("date_dim", "item", "store_sales"):
        sess.register_table(
            name,
            MS(ds_batches(ds[name], TPCDS_SCHEMAS[name], N_PARTS, batch_rows=4096),
               TPCDS_SCHEMAS[name]),
        )
    # exprIds: date_dim 1-6 (d_date_sk=1, d_year=3, d_moy=4),
    # item 10+ (i_item_sk=10, i_brand_id=11, i_brand=12,
    # i_manufact_id=13), store_sales 30+ (ss_sold_date_sk=30,
    # ss_item_sk=31, ss_ext_sales_price=32)
    d_sk = F.attr("d_date_sk", 1)
    d_year = F.attr("d_year", 3, "integer")
    d_moy = F.attr("d_moy", 4, "integer")
    i_sk = F.attr("i_item_sk", 10)
    i_bid = F.attr("i_brand_id", 11, "integer")
    i_brand = F.attr("i_brand", 12, "string")
    i_mfg = F.attr("i_manufact_id", 13, "integer")
    ss_d = F.attr("ss_sold_date_sk", 30)
    ss_i = F.attr("ss_item_sk", 31)
    ss_p = F.attr("ss_ext_sales_price", 32, "decimal(7,2)")

    dt = F.project([d_sk, d_year], F.filter_(
        F.binop("EqualTo", d_moy, F.lit(11, "integer")),
        F.scan("date_dim", [d_sk, d_year, d_moy])))
    # this generator's 60-item slice has no manufact 128; pick one
    # that exists so the differential is non-trivial
    mfg_id = int(ds["item"]["i_manufact_id"][0][0])
    it = F.project([i_sk, i_bid, i_brand], F.filter_(
        F.binop("EqualTo", i_mfg, F.lit(mfg_id, "integer")),
        F.scan("item", [i_sk, i_bid, i_brand, i_mfg])))
    sales = F.scan("store_sales", [ss_d, ss_i, ss_p])
    j1 = F.bhj([d_sk], [ss_d], "Inner", "left", F.broadcast(dt), sales)
    j2 = F.bhj([i_sk], [ss_i], "Inner", "left", F.broadcast(it), j1)
    groupings = [d_year, i_bid, i_brand]
    agg = two_stage(
        groupings, [(F.sum_(ss_p), 200)], j2, N_PARTS,
    )
    out = F.take_ordered(
        100,
        [F.sort_order(d_year), F.sort_order(F.attr("sum_agg", 200, "decimal(17,2)"), asc=False),
         F.sort_order(i_bid)],
        [F.alias(d_year, "d_year", 300),
         F.alias(F.attr("sum_agg", 200, "decimal(17,2)"), "sum_agg", 301),
         F.alias(i_bid, "brand_id", 302), F.alias(i_brand, "brand", 303)],
        agg,
    )
    got = sess.execute(json.dumps(F.flatten(out)))
    from blaze_tpu.tpcds.oracle import _brand_rollup
    from test_tpcds import _check_brand_report
    exp = _brand_rollup(ds, year=None, moy=11, item_filter_col="i_manufact_id",
                        item_filter_val=mfg_id,
                        group_cols=["i_brand_id", "i_brand"])
    assert exp, "oracle matched no rows"
    _check_brand_report(got, exp, "sum_agg")
    assert got["d_year"] == sorted(got["d_year"])


# ------------------------------------------- vendored 3.5.1 dumps (r4)

def _load_dump(name):
    import os

    path = os.path.join(os.path.dirname(__file__), "fixtures", name)
    with open(path) as f:
        js = f.read()
    # the dumps must carry the real-Spark encodings, like the q6 one
    assert '"jvmId"' in js and '"product-class"' in js
    return js


def test_spark351_dump_q1(sess, data):
    """Real-format q1: two-stage avg/sum/count set, range-partitioned
    exchange + global sort above the final aggregate."""
    js = _load_dump("spark351_q1_plan.json")
    assert "RangePartitioning" in js and "aggregate.Average" in js
    got = sess.execute(js)
    exp = O.oracle_q1(data)
    keys = list(zip(got["l_returnflag"], got["l_linestatus"]))
    assert keys == sorted(keys) and set(keys) == set(exp)
    for i, k in enumerate(keys):
        e = exp[k]
        for m in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
                  "count_order", "avg_qty", "avg_price", "avg_disc"):
            assert got[m][i] == e[m], (k, m)


def _check_dump_q3(sess, data, name, expect_marker):
    js = _load_dump(name)
    assert expect_marker in js
    got = sess.execute(js)
    exp = O.oracle_q3(data)
    rows = list(zip(got["l_orderkey"], got["revenue"],
                    got["o_orderdate"], got["o_shippriority"]))
    assert len(rows) == len(exp)
    assert set((r[0], r[1]) for r in rows) == set((r[0], r[1]) for r in exp)
    assert [r[1] for r in rows] == sorted((r[1] for r in rows), reverse=True)


def test_spark351_dump_q3_bhj(sess, data):
    """Real-format q3 under the default broadcast threshold: two
    BuildLeft broadcast hash joins w/ HashedRelationBroadcastMode."""
    _check_dump_q3(sess, data, "spark351_q3_bhj_plan.json",
                   "HashedRelationBroadcastMode")


def test_spark351_dump_q3_smj(sess, data):
    """Real-format q3 with broadcasts disabled: exchange -> sort ->
    SortMergeJoin on both joins."""
    _check_dump_q3(sess, data, "spark351_q3_smj_plan.json",
                   "SortMergeJoinExec")


def test_spark351_dump_q3_smj_adaptive(sess, data):
    """The reference's AQE analogy end to end: the REAL-format SMJ q3
    dump (broadcasts disabled) crosses catalyst conversion and the
    scheduler's adaptive pass (spark.blaze.enable.adaptiveJoin)
    re-plans its small-side joins as broadcast joins mid-run — swap
    PROVEN by stage inspection, result equal to the non-adaptive run."""
    from blaze_tpu import conf
    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.ops.joins import BroadcastJoinExec
    from blaze_tpu.runtime.scheduler import run_stages, split_stages

    js = _load_dump("spark351_q3_smj_plan.json")
    base = sess.execute_distributed(js)

    stages, manager = split_stages(sess.plan(js))
    old = conf.ADAPTIVE_JOIN_ENABLE.get()
    conf.ADAPTIVE_JOIN_ENABLE.set(True)
    try:
        got = {}
        for b in run_stages(stages, manager):
            d = batch_to_pydict(b)
            for k, v in d.items():
                got.setdefault(k, []).extend(v)
    finally:
        conf.ADAPTIVE_JOIN_ENABLE.set(old)

    def has_bhj(stages_):
        def walk(n):
            if isinstance(n, BroadcastJoinExec):
                return True
            return any(walk(c) for c in n.children)
        return any(walk(s.plan) for s in stages_)

    assert has_bhj(stages), "adaptive pass did not swap any join"
    assert sorted(zip(*got.values())) == sorted(zip(*base.values()))
    exp = O.oracle_q3(data)
    rows = list(zip(got["l_orderkey"], got["revenue"]))
    assert set(rows) == set((r[0], r[1]) for r in exp)
