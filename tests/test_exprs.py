"""Expression engine: Spark null/arithmetic/cast semantics.

Mirrors the reference's expr/function unit tests (datafusion-ext-exprs,
datafusion-ext-functions, ext-commons cast.rs) as behavior checks."""

import datetime

import numpy as np
import pytest

from blaze_tpu.batch import RecordBatch, batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.compile import host_eval, lower, needs_host, split_host_exprs
from blaze_tpu.exprs.ir import Case, InList, Like, ScalarFunc, func
from blaze_tpu.schema import DataType, Field, Schema


def _eval(expr, batch):
    cols = {f.name: c for f, c in zip(batch.schema.fields, batch.columns)}
    return lower(expr, batch.schema, cols, batch.capacity)


def _vals(expr, batch, n=None):
    c = _eval(expr, batch)
    n = n or batch.num_rows
    data = np.asarray(c.data)[:n]
    valid = np.asarray(c.validity)[:n]
    out = []
    for i in range(n):
        if not valid[i]:
            out.append(None)
        elif c.dtype.kind.name == "BOOL":
            out.append(bool(data[i]))
        elif c.dtype.is_float:
            out.append(float(data[i]))
        else:
            out.append(int(data[i]))
    return out


@pytest.fixture
def nums():
    schema = Schema([
        Field("a", DataType.int32()),
        Field("b", DataType.int32()),
        Field("f", DataType.float64()),
    ])
    return batch_from_pydict(
        {"a": [1, 2, None, 4], "b": [10, 0, 30, None], "f": [1.5, -2.5, 0.0, None]},
        schema,
    )


def test_arith_null_propagation(nums):
    assert _vals(col("a") + col("b"), nums) == [11, 2, None, None]
    assert _vals(col("a") * lit(3), nums) == [3, 6, None, 12]


def test_division_semantics(nums):
    # Spark: `/` on ints -> double; x/0 -> null
    assert _vals(col("a") / col("b"), nums) == [0.1, None, None, None]


def test_modulo_sign(nums):
    # Java % has dividend sign
    schema = Schema([Field("x", DataType.int32()), Field("y", DataType.int32())])
    b = batch_from_pydict({"x": [7, -7, 7, -7], "y": [3, 3, -3, -3]}, schema)
    assert _vals(col("x") % col("y"), b) == [1, -1, 1, -1]


def test_comparison_and_null(nums):
    assert _vals(col("a") < col("b"), nums) == [True, False, None, None]
    assert _vals(col("a").is_null(), nums) == [False, False, True, False]
    assert _vals(col("a").is_not_null(), nums) == [True, True, False, True]


def test_three_valued_logic():
    schema = Schema([Field("p", DataType.bool_()), Field("q", DataType.bool_())])
    b = batch_from_pydict(
        {"p": [True, True, False, None, None, False], "q": [None, True, None, None, False, False]},
        schema,
    )
    # Spark: true AND null = null; false AND null = false
    assert _vals(col("p") & col("q"), b) == [None, True, False, None, False, False]
    # true OR null = true; false OR null = null
    assert _vals(col("p") | col("q"), b) == [True, True, None, None, None, False]
    assert _vals(~col("p"), b) == [False, False, True, None, None, True]


def test_decimal_arithmetic():
    d = DataType.decimal(12, 2)
    schema = Schema([Field("x", d), Field("y", d)])
    b = batch_from_pydict({"x": [1.50, 2.25, None], "y": [0.50, 3.00, 1.00]}, schema)
    # + keeps scale 2 -> unscaled ints at scale 2
    assert _vals(col("x") + col("y"), b) == [200, 525, None]
    # * -> scale 4
    assert _vals(col("x") * col("y"), b) == [7500, 67500, None]
    # 1 - x at scale 2
    assert _vals(lit(1).cast(DataType.decimal(12, 2)) - col("y"), b) == [50, -200, 0]


def test_decimal_division_exact_path():
    d = DataType.decimal(4, 1)
    schema = Schema([Field("x", d), Field("y", d)])
    b = batch_from_pydict({"x": [1.0, 7.0], "y": [3.0, 2.0]}, schema)
    c = _eval(col("x") / col("y"), b)
    s = c.dtype.scale
    got = [v / 10**s for v in _vals(col("x") / col("y"), b)]
    assert abs(got[0] - 1 / 3) < 10 ** -(s - 1)
    assert got[1] == 3.5


def test_cast_overflow_wraps():
    schema = Schema([Field("x", DataType.int64())])
    b = batch_from_pydict({"x": [300, -1, 2**40]}, schema)
    assert _vals(col("x").cast(DataType.int8()), b) == [44, -1, 0]


def test_cast_float_to_int_java():
    schema = Schema([Field("x", DataType.float64())])
    b = batch_from_pydict({"x": [2.9, -2.9, float("nan"), 1e20]}, schema)
    got = _vals(col("x").cast(DataType.int32()), b)
    assert got[0] == 2 and got[1] == -2 and got[2] == 0 and got[3] == 2**31 - 1


def test_cast_decimal_overflow_null():
    schema = Schema([Field("x", DataType.decimal(10, 2))])
    b = batch_from_pydict({"x": [123.45, 99999999.99]}, schema)
    got = _vals(col("x").cast(DataType.decimal(5, 2)), b)
    assert got[0] == 12345 and got[1] is None


def test_string_compare():
    schema = Schema([Field("s", DataType.string(16))])
    b = batch_from_pydict({"s": ["apple", "banana", None, "apricot"]}, schema)
    assert _vals(col("s") == lit("banana"), b) == [False, True, None, False]
    assert _vals(col("s") < lit("b"), b) == [True, False, None, True]
    assert _vals(col("s") >= lit("apricot"), b) == [False, True, None, True]


def test_in_list():
    schema = Schema([Field("s", DataType.string(16))])
    b = batch_from_pydict({"s": ["MAIL", "SHIP", "AIR", None]}, schema)
    assert _vals(col("s").isin("MAIL", "SHIP"), b) == [True, True, False, None]


def test_like_device_patterns():
    schema = Schema([Field("s", DataType.string(32))])
    b = batch_from_pydict(
        {"s": ["PROMO burnished", "STANDARD brushed", "small PROMO", None]}, schema
    )
    assert _vals(Like(col("s"), "PROMO%"), b) == [True, False, False, None]
    assert _vals(Like(col("s"), "%PROMO%"), b) == [True, False, True, None]
    assert _vals(Like(col("s"), "%brushed"), b) == [False, True, False, None]
    assert _vals(Like(col("s"), "PROMO burnished"), b) == [True, False, False, None]


def test_like_host_fallback():
    schema = Schema([Field("s", DataType.string(64))])
    b = batch_from_pydict(
        {"s": ["one special two requests", "special", "requests special", None]}, schema
    )
    e = Like(col("s"), "%special%requests%")
    assert needs_host(e)
    new_exprs, host_parts = split_host_exprs([e])
    assert len(host_parts) == 1
    hcol = host_eval(host_parts[0][1], b)
    got = [
        None if not np.asarray(hcol.validity)[i] else bool(np.asarray(hcol.data)[i])
        for i in range(b.num_rows)
    ]
    assert got == [True, False, False, None]


def test_case_when():
    schema = Schema([Field("x", DataType.int32())])
    b = batch_from_pydict({"x": [1, 5, None, 10]}, schema)
    e = Case([(col("x") < lit(3), lit(100)), (col("x") < lit(7), lit(200))], lit(300))
    assert _vals(e, b) == [100, 200, 300, 300]
    e2 = Case([(col("x") < lit(3), lit(100))])
    assert _vals(e2, b) == [100, None, None, None]


def test_date_parts():
    schema = Schema([Field("d", DataType.date32())])
    days = [
        (datetime.date(1994, 1, 1) - datetime.date(1970, 1, 1)).days,
        (datetime.date(2000, 2, 29) - datetime.date(1970, 1, 1)).days,
        (datetime.date(1969, 12, 31) - datetime.date(1970, 1, 1)).days,
    ]
    b = batch_from_pydict({"d": days}, schema)
    assert _vals(func("year", col("d")), b) == [1994, 2000, 1969]
    assert _vals(func("month", col("d")), b) == [1, 2, 12]
    assert _vals(func("day", col("d")), b) == [1, 29, 31]


def test_date_literal_compare():
    schema = Schema([Field("d", DataType.date32())])
    day = (datetime.date(1994, 3, 1) - datetime.date(1970, 1, 1)).days
    b = batch_from_pydict({"d": [day - 1, day, day + 1]}, schema)
    e = col("d") >= lit(datetime.date(1994, 3, 1))
    assert _vals(e, b) == [False, True, True]


def test_substring_concat_upper():
    schema = Schema([Field("s", DataType.string(16))])
    b = batch_from_pydict({"s": ["hello", "ab", None]}, schema)
    sub = func("substring", col("s"), lit(2), lit(3))
    c = _eval(sub, b)
    from blaze_tpu.batch import strings_to_list

    assert strings_to_list(c.to_host(), 3) == ["ell", "b", None]
    up = func("upper", col("s"))
    assert strings_to_list(_eval(up, b).to_host(), 3) == ["HELLO", "AB", None]
    cc = func("concat", col("s"), lit("!x"))
    assert strings_to_list(_eval(cc, b).to_host(), 3) == ["hello!x", "ab!x", None]


def test_coalesce():
    schema = Schema([Field("x", DataType.int32()), Field("y", DataType.int32())])
    b = batch_from_pydict({"x": [None, 2, None], "y": [1, 5, None]}, schema)
    assert _vals(func("coalesce", col("x"), col("y")), b) == [1, 2, None]
