"""C++ gateway driven from pytest over ctypes — the same JDK-free
boundary path as native/tests/gateway_test.cc:

TaskDefinition bytes -> bt_gateway_call_native (producer thread +
bounded channel, ≙ exec.rs:46-142 / rt.rs:57-215) -> per-batch Arrow
C-FFI export -> callback imports (strings included) -> compare against
direct plan execution.
"""

import ctypes as C
import os

import numpy as np
import pytest

from blaze_tpu import native
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict, concat_batches
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import ScalarFunc
from blaze_tpu.gateway import import_batch_ffi
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.serde.to_proto import task_definition

_GW_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "build", "libblaze_gateway.so",
)

pytestmark = pytest.mark.skipif(
    not os.path.exists(_GW_PATH) or native._load() is None,
    reason="native gateway not built (cmake -S native -B native/build)",
)


class _Callbacks(C.Structure):
    _fields_ = [
        ("user", C.c_void_p),
        ("import_batch", C.CFUNCTYPE(None, C.c_void_p, C.c_size_t)),
        ("set_error", C.CFUNCTYPE(None, C.c_void_p, C.c_char_p)),
    ]


def _gateway():
    lib = C.CDLL(_GW_PATH)  # CDLL releases the GIL during calls
    lib.bt_gateway_call_native.argtypes = [C.c_char_p, C.c_int64, C.POINTER(_Callbacks)]
    lib.bt_gateway_call_native.restype = C.c_void_p
    lib.bt_gateway_next_batch.argtypes = [C.c_void_p]
    lib.bt_gateway_next_batch.restype = C.c_int32
    lib.bt_gateway_last_error.argtypes = [C.c_void_p]
    lib.bt_gateway_last_error.restype = C.c_char_p
    lib.bt_gateway_finalize.argtypes = [C.c_void_p]
    return lib


def _drive(lib, td: bytes, out_schema):
    batches = []
    errors = []

    @C.CFUNCTYPE(None, C.c_void_p, C.c_size_t)
    def on_import(_user, addr):
        batches.append(import_batch_ffi(addr, out_schema))

    @C.CFUNCTYPE(None, C.c_void_p, C.c_char_p)
    def on_error(_user, msg):
        errors.append((msg or b"").decode())

    cbs = _Callbacks(None, on_import, on_error)
    rt = lib.bt_gateway_call_native(td, len(td), C.byref(cbs))
    try:
        while True:
            rc = lib.bt_gateway_next_batch(rt)
            if rc == 1:
                continue
            return batches, errors, rc
    finally:
        lib.bt_gateway_finalize(rt)


def test_gateway_end_to_end_with_strings():
    schema = Schema([Field("x", DataType.int64()), Field("s", DataType.string(8))])
    b = batch_from_pydict(
        {"x": [1, 2, None, 4, 5], "s": ["ab", "cd", None, "ef", "gh"]}, schema
    )
    plan = ProjectExec(
        MemoryScanExec([[b]], schema),
        [(col("x") + lit(10)).alias("y"), ScalarFunc("upper", [col("s")]).alias("u")],
    )
    td = task_definition(plan, "pytest", 0, 0)

    expected = batch_to_pydict(list(plan.execute(0, TaskContext(0, 1)))[0])

    lib = _gateway()
    batches, errors, rc = _drive(lib, td, plan.schema)
    assert rc == 0 and not errors
    got = batch_to_pydict(concat_batches(batches))
    assert got["y"] == expected["y"] == [11, 12, None, 14, 15]
    assert got["u"] == expected["u"] == ["AB", "CD", None, "EF", "GH"]


def test_gateway_error_contract():
    lib = _gateway()
    batches, errors, rc = _drive(
        lib, b"\xde\xad\xbe\xef", Schema([Field("x", DataType.int64())])
    )
    assert rc == -1
    assert batches == []
    assert errors and errors[0]


def test_gateway_multi_batch_ordering():
    schema = Schema([Field("x", DataType.int64())])
    bs = [
        batch_from_pydict({"x": list(range(i * 10, i * 10 + 10))}, schema)
        for i in range(5)
    ]
    plan = ProjectExec(MemoryScanExec([bs], schema), [(col("x") * lit(2)).alias("d")])
    td = task_definition(plan, "pytest", 0, 0)
    lib = _gateway()
    batches, errors, rc = _drive(lib, td, plan.schema)
    assert rc == 0 and not errors
    got = [v for b in batches for v in batch_to_pydict(b)["d"]]
    assert got == [2 * v for v in range(50)]
