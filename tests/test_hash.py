"""Spark-exact hash golden tests.

Expected values are Spark-generated vectors recorded in the reference's
unit suite (datafusion-ext-commons/src/spark_hash.rs:438-543, themselves
generated with Spark's Murmur3Hash/XxHash64 expressions) — behavioral
parity targets, independently reimplemented here.
"""

import numpy as np
import pytest

from blaze_tpu.batch import column_from_numpy, column_from_strings
from blaze_tpu.exprs.hash import murmur3_columns, pmod, xxhash64_columns
from blaze_tpu.schema import DataType


def _u(x):
    return np.int32(np.uint32(x))


def test_murmur3_i32():
    col = column_from_numpy(DataType.int32(), np.array([1, 2, 3, 4], np.int32))
    h = np.asarray(murmur3_columns([col]))[:4]
    assert h.tolist() == [-559580957, 1765031574, -1823081949, -397064898]


def test_murmur3_i8():
    vals = np.array([1, 0, -1, 127, -128], np.int8)
    col = column_from_numpy(DataType.int8(), vals)
    h = np.asarray(murmur3_columns([col]))[:5]
    expected = [_u(0xDEA578E3), _u(0x379FAE8F), _u(0xA0590E3D), _u(0x43B4D8ED), _u(0x422A1365)]
    assert h.tolist() == expected


def test_murmur3_i64():
    vals = np.array([1, 0, -1, np.iinfo(np.int64).max, np.iinfo(np.int64).min], np.int64)
    col = column_from_numpy(DataType.int64(), vals)
    h = np.asarray(murmur3_columns([col]))[:5]
    expected = [_u(0x99F0149D), _u(0x9C67B85D), _u(0xC8008529), _u(0xA05B5D7B), _u(0xCD1E64FB)]
    assert h.tolist() == expected


def test_murmur3_str():
    col = column_from_strings(["hello", "bar", "", "😁", "天地"])
    h = np.asarray(murmur3_columns([col]))[:5]
    expected = [_u(3286402344), _u(2486176763), _u(142593372), _u(885025535), _u(2395000894)]
    assert h.tolist() == expected


def test_xxhash64_i64():
    vals = np.array([1, 0, -1, np.iinfo(np.int64).max], np.int64)
    col = column_from_numpy(DataType.int64(), vals)
    h = np.asarray(xxhash64_columns([col]))[:4]
    assert h.tolist() == [
        -7001672635703045582,
        -5252525462095825812,
        3858142552250413010,
        -3246596055638297850,
    ]


def test_xxhash64_str():
    col = column_from_strings(["hello", "bar", "", "😁", "天地"])
    h = np.asarray(xxhash64_columns([col]))[:5]
    assert h.tolist() == [
        -4367754540140381902,
        -1798770879548125814,
        -7444071767201028348,
        -6337236088984028203,
        -235771157374669727,
    ]


def test_null_leaves_hash_unchanged():
    vals = np.array([1, 1], np.int32)
    validity = np.array([True, False])
    col = column_from_numpy(DataType.int32(), vals, validity)
    h = np.asarray(murmur3_columns([col]))[:2]
    assert h[0] == -559580957
    assert h[1] == 42  # seed passes through for null


def test_multi_column_chaining():
    a = column_from_numpy(DataType.int32(), np.array([1], np.int32))
    b = column_from_numpy(DataType.int64(), np.array([7], np.int64))
    h2 = np.asarray(murmur3_columns([a, b]))[:1]
    # chained = hashLong(7, seed=hashInt(1, 42)); verify vs a direct
    # recomputation through the same primitives but unchained semantics
    h_a = np.asarray(murmur3_columns([a]))[0]
    assert h2[0] != h_a  # chaining must change the hash


def test_pmod_negative():
    import jax.numpy as jnp

    pids = np.asarray(pmod(jnp.array([-3, 3, -200], jnp.int32), 7))
    assert (pids >= 0).all() and (pids < 7).all()
    assert pids[1] == 3


def test_long_string_stripes():
    # >32 bytes exercises the xxhash64 stripe path; equal prefixes with
    # different tails must differ
    s1 = "a" * 40
    s2 = "a" * 39 + "b"
    col = column_from_strings([s1, s2])
    h = np.asarray(xxhash64_columns([col]))[:2]
    assert h[0] != h[1]
    m = np.asarray(murmur3_columns([col]))[:2]
    assert m[0] != m[1]


def test_f64_bits_arithmetic_equals_view():
    """_f64_bits (the bitcast-free path TPU requires) must reproduce
    numpy's raw bit view for every f64 class except non-canonical NaN."""
    import jax.numpy as jnp
    from blaze_tpu.exprs.hash import _f64_bits

    rng = np.random.default_rng(11)
    vals = np.concatenate([
        np.array([0.0, 1.0, -1.0, 2.0, 0.5, 1.5, np.pi, -np.pi, 1e300, -1e300,
                  1e-300, 2.2250738585072014e-308,          # min normal
                  1.7976931348623157e308,                    # max finite
                  np.inf, -np.inf]),
        (rng.random(500) * 2 - 1) * 1.7e308,
        rng.random(500) * 2e-300 + 1e-305,
        2.0 ** rng.integers(-1022, 1023, 500) * (1 + rng.random(500)),
        np.nextafter(2.0 ** rng.integers(-1000, 1000, 200).astype(np.float64), np.inf),
        np.nextafter(2.0 ** rng.integers(-1000, 1000, 200).astype(np.float64), -np.inf),
    ])
    got = np.asarray(_f64_bits(jnp.asarray(vals)))
    want = vals.view(np.int64)
    np.testing.assert_array_equal(got, want)
    # canonical NaN
    assert int(np.asarray(_f64_bits(jnp.asarray(np.array([np.nan]))))[0]) == 0x7FF8 << 48
    # subnormals: XLA flushes denormals (DAZ/FTZ) — they hash as zero
    sub = np.asarray(_f64_bits(jnp.asarray(np.array([5e-324, -5e-324]))))
    assert set(sub.tolist()) <= {0, 1, -(2**63), -(2**63) | 1}
