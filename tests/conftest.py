"""Test harness configuration.

Per the build contract, all tests run on a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware; the driver
separately dry-runs the multi-chip path and benches on a real chip.

This mirrors the reference's test strategy (SURVEY.md §4): unit tests
run the operators "pure native" with the JVM bridge stubbed by absence;
here kernels run pure-JAX with the gateway absent.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize force-selects jax_platforms="axon,cpu"; the
# config (not the env var) is authoritative, so override it here or
# every test run dials the TPU tunnel.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def _ensure_native_built() -> None:
    """A fresh checkout has no native/build (gitignored build output);
    several suites (gateway FFI, UDF wire, batch serde differentials)
    hard-require libblaze_tpu_native.so.  Build it once up front with
    the baked-in toolchain instead of failing 40 tests in."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lib = os.path.join(repo, "native", "build", "libblaze_tpu_native.so")
    if os.path.exists(lib) or os.environ.get("BLAZE_TPU_NATIVE_LIB"):
        return
    src = os.path.join(repo, "native")
    try:
        subprocess.run(["cmake", "-B", "build", "-G", "Ninja",
                        "-DCMAKE_BUILD_TYPE=Release"], cwd=src, check=True,
                       capture_output=True, timeout=300)
        subprocess.run(["ninja", "-C", "build"], cwd=src, check=True,
                       capture_output=True, timeout=600)
    except Exception as e:  # noqa: BLE001 — tests that need the lib
        print(f"conftest: native build failed ({e}); FFI tests will fail")


_ensure_native_built()


import pytest

# Per the static-analysis contract (ISSUE 6): the plan verifier runs
# over every optimized plan in EVERY test — any plan a test executes
# through optimize_plan/run_task that breaks a structural invariant
# (schema edge, distribution/ordering prerequisite, fusion invariant)
# fails loudly here instead of producing wrong answers.
from blaze_tpu import conf as _blaze_conf  # noqa: E402

_blaze_conf.VERIFY_PLAN.set(True)


@pytest.fixture(autouse=True, scope="module")
def _clear_compiled_caches_between_modules():
    """Free compiled XLA executables between test MODULES.

    jaxlib's CPU backend segfaults inside backend_compile_and_load
    once enough compiled programs accumulate in one process (~44 slow
    differential tests in; deterministic, single-threaded, independent
    of thread stack size).  Per-module cache clearing keeps the full
    single-process `pytest tests/` run under that ceiling at the cost
    of recompiling shared kernels per module."""
    yield
    import jax

    from blaze_tpu.ops.joins.broadcast import clear_join_map_cache
    from blaze_tpu.runtime.kernel_cache import clear_kernel_cache

    clear_kernel_cache()
    clear_join_map_cache()
    jax.clear_caches()
