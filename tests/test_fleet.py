"""Fleet observability: worker telemetry aggregation (runtime/worker.py
frames -> hostpool fold -> monitor fleet registry), per-pool SLO
burn-rate alerts (runtime/slo.py), and incident debug bundles
(runtime/bundle.py).

The reconcile contract under test: every telemetry delta a pooled
worker reports rides its job's ``done`` frame, so the driver's fleet
registry (``/workers``), the ``worker_telemetry`` event log, and the
pool's own commit ledger must all agree — three independent fold paths
of the same frames.  The SLO layer is pure burn-rate math over a
sample ring, so its fire/hold/resolve transitions are unit-testable
without sleeping through real windows.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from blaze_tpu import conf
from blaze_tpu.runtime import bundle, monitor, slo, trace, trace_report
from blaze_tpu.runtime.hostpool import HostPool

import spark_fixtures as F  # noqa: F401 — test_hostpool helpers need it
from test_hostpool import _run, _two_stage_plan, _write_parquet_inputs

POOL = "fleet_t"


@pytest.fixture
def armed_monitor():
    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_PORT.set(0)
    monitor.reset()
    try:
        yield monitor
    finally:
        monitor.shutdown_server()
        conf.MONITOR_ENABLE.set(False)
        conf.MONITOR_PORT.set(4048)
        monitor.reset()
        assert monitor.monitor_threads() == []


@pytest.fixture
def armed_slo():
    """SLO layer armed with a permissive eval throttle so ONLY the
    test's forced evaluations advance the alert state machine (the
    first observe() still runs one opportunistic pass)."""
    conf.SLO_ENABLE.set(True)
    conf.SLO_EVAL_INTERVAL_MS.set(60_000)
    conf.SLO_RESOLVE_HOLD_EVALS.set(2)
    conf.set_conf(f"spark.blaze.slo.pool.{POOL}.errorRate", 0.5)
    conf.set_conf(f"spark.blaze.slo.pool.{POOL}.targetWindowSec", 30.0)
    slo.reset()
    try:
        yield slo
    finally:
        conf.SLO_ENABLE.set(False)
        conf.SLO_EVAL_INTERVAL_MS.set(200)
        conf.SLO_RESOLVE_HOLD_EVALS.set(2)
        conf.set_conf(f"spark.blaze.slo.pool.{POOL}.errorRate", None)
        conf.set_conf(f"spark.blaze.slo.pool.{POOL}.targetWindowSec", None)
        slo.reset()


# --------------------------------------------- 1. burn-rate math

def test_burn_rate_math():
    # burn = observed bad fraction / budgeted bad fraction
    assert slo.burn_rate(1, 100, 0.01) == pytest.approx(1.0)
    assert slo.burn_rate(5, 100, 0.01) == pytest.approx(5.0)
    assert slo.burn_rate(1, 4, 0.5) == pytest.approx(0.5)
    # no evidence is not a violation; zero budget = objective disabled
    assert slo.burn_rate(0, 0, 0.01) == 0.0
    assert slo.burn_rate(3, 10, 0.0) == 0.0


def test_fast_window_is_slow_over_12_with_floor():
    assert slo.fast_window_sec(3600.0) == pytest.approx(300.0)
    assert slo.fast_window_sec(60.0) == pytest.approx(5.0)
    # pathologically small target windows still integrate >1 sample
    assert slo.fast_window_sec(0.1) == pytest.approx(0.05)


def test_slo_disabled_is_structural_noop():
    conf.SLO_ENABLE.set(False)
    slo.reset()
    slo.observe(POOL, 9.9, ok=False)
    assert slo.evaluate(force=True) == []
    doc = slo.doc()
    assert doc["enabled"] is False
    assert doc["pools"] == {}


def test_alert_fires_on_both_windows_and_resolve_holds(armed_slo):
    """Fire: both the fast and slow windows burn past the threshold.
    Resolve: FLAP-SUPPRESSED — the alert must stay below the threshold
    for resolveHoldEvals consecutive evaluations before clearing."""
    def _state():
        return slo.doc()["pools"][POOL]["slos"]["error_rate"]

    slo.observe(POOL, 0.01, ok=False)
    slo.observe(POOL, 0.01, ok=False)
    slo.evaluate(force=True)
    st = _state()
    assert st["firing"] is True
    assert st["burn_fast"] >= 1.0 and st["burn_slow"] >= 1.0
    # recovery traffic dilutes the bad fraction below the budget ...
    for _ in range(6):
        slo.observe(POOL, 0.01, ok=True)
    slo.evaluate(force=True)   # below #1: held, still firing
    assert _state()["firing"] is True
    slo.evaluate(force=True)   # below #2: resolves
    st = _state()
    assert st["firing"] is False
    assert st["burn_fast"] < 1.0


def test_pool_with_no_objectives_never_alerts(armed_slo):
    slo.observe("no_slo_pool", 99.0, ok=False)
    assert slo.evaluate(force=True) == []
    pdoc = slo.doc()["pools"]["no_slo_pool"]
    assert pdoc["objectives"] is None
    assert pdoc["slos"] == {}


# ----------------------------------- 2. alert event reconciliation

def _ev(etype, **fields):
    return {"ts": 1.0, "type": etype, **fields}


def test_reconcile_slo_alerts_pairs_and_terminal_firing():
    events = [
        _ev("slo_alert_firing", pool="etl", slo="latency"),
        _ev("slo_alert_resolved", pool="etl", slo="latency"),
        _ev("slo_alert_firing", pool="etl", slo="error_rate"),
    ]
    rec = trace_report.reconcile_slo_alerts(events)
    assert rec["fired"] == 2 and rec["resolved"] == 1
    # an alert still firing at end-of-log is a legitimate terminal
    # state (the incident is ongoing) — reported, not an error
    assert [(e["pool"], e["slo"]) for e in rec["still_firing"]] == \
        [("etl", "error_rate")]
    assert rec["reconciled"] is True


def test_reconcile_slo_alerts_orphan_resolve_fails():
    # a resolve with no prior firing means the pairing is broken
    rec = trace_report.reconcile_slo_alerts(
        [_ev("slo_alert_resolved", pool="etl", slo="latency")])
    assert [(e["pool"], e["slo"]) for e in rec["orphan_resolves"]] == \
        [("etl", "latency")]
    assert rec["reconciled"] is False


# ------------------------------- 3. two-worker telemetry reconcile

def test_two_worker_telemetry_reconciles_with_driver(
        tmp_path, armed_monitor):
    """TWO pooled workers run the map stage with tracing armed; the
    fleet registry (``/workers``), the merged ``worker_telemetry``
    event log, and the pool's commit ledger must agree on the totals
    — three independent fold paths of the same done frames."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path / "evlog"))
    trace.reset()
    try:
        files, _data = _write_parquet_inputs(tmp_path)
        sess, plan_json = _two_stage_plan(files)
        with monitor.query_span("fleet_reconcile", mode="scheduler"):
            with HostPool(2) as pool:
                _run(sess, plan_json, tmp_path / "shuffle_pool",
                     pool=pool)
                owned = pool.owned_map_outputs()
                snap = monitor.workers_snapshot()
                url = monitor.ensure_server().url
                with urllib.request.urlopen(url + "/workers",
                                            timeout=10) as r:
                    via_http = json.loads(r.read())
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()

    assert owned == 3
    rows = snap["workers"]
    assert len(rows) == 2 and {w["name"] for w in rows} == {"w0", "w1"}
    assert snap["pool"]["workers"] == 2 and not snap["pool"]["degraded"]
    # registry vs pool commit ledger: every pooled map task is one ok
    # job on exactly one worker
    assert sum(w["jobs_ok"] for w in rows) == owned
    assert sum(w["jobs_failed"] for w in rows) == 0
    # traced run: the kernel capture split rode the telemetry
    assert sum(w["device_ns"] for w in rows) > 0
    # registry vs event log: per-field sums match exactly
    events = trace_report.merge_event_logs(
        trace_report.event_log_files(str(tmp_path / "evlog")))
    wt = [e for e in events if e["type"] == "worker_telemetry"]
    assert len(wt) == owned
    for field in monitor.WORKER_TM_FIELDS:
        ev_sum = sum(int(e.get(field, 0) or 0) for e in wt)
        assert ev_sum == sum(w[field] for w in rows), field
    # the HTTP endpoint serves the same registry document
    assert {w["name"]: w["jobs_ok"] for w in via_http["workers"]} == \
        {w["name"]: w["jobs_ok"] for w in rows}
    # trace_report's offline fleet section folds the same events
    rep = trace_report.render_json(events)
    assert set(rep["workers"]) == {"w0", "w1"}
    assert sum(w["jobs_ok"] for w in rep["workers"].values()) == owned


def test_workers_endpoint_404_without_fleet(armed_monitor):
    url = monitor.ensure_server().url
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url + "/workers", timeout=10)
    assert ei.value.code == 404


# ------------------------------------------ 4. monitor surfaces

class _FakePool:
    def stats(self):
        return {"workers": 2, "live": 1, "lost": 1,
                "blacklisted": 0, "degraded": False}


def test_healthz_pool_block_golden_keys(armed_monitor):
    pool = _FakePool()
    monitor.register_pool(pool)
    doc = monitor.healthz_doc()
    assert set(doc["pool"]) == set(monitor.HEALTHZ_POOL_KEYS)
    for ep in ("/workers", "/slo", "POST /queries/<id>/bundle"):
        assert ep in doc["endpoints"]


def test_statsd_lines_carry_fleet_and_slo_gauges(armed_monitor,
                                                 armed_slo):
    monitor.worker_register("w0", 4242)
    monitor.worker_beat("w0", 4242, {"jobs_ok": 3, "rows": 100,
                                     "bytes": 2048, "device_ns": 500})
    slo.observe(POOL, 0.01, ok=False)
    slo.observe(POOL, 0.01, ok=False)
    slo.evaluate(force=True)
    lines = monitor.render_statsd_lines()
    # label values ride as dotted name suffixes (blaze_worker_jobs_ok.w0)
    names = {ln.split(":", 1)[0] for ln in lines}
    for family in ("blaze_worker_jobs_ok", "blaze_worker_rows_total",
                   "blaze_slo_burn_rate_fast", "blaze_slo_alert_firing"):
        assert any(n.startswith(family) for n in names), family
    # histogram buckets never ride the gauge transport
    assert not any("_bucket" in ln for ln in lines)


def test_watch_json_mode_emits_pure_jsonl(armed_monitor, capsys):
    from blaze_tpu.__main__ import _watch

    with monitor.query_span("watch_json_q", mode="in-process"):
        pass
    url = monitor.ensure_server().url
    assert _watch(url, 0.01, 2, json_out="-") == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 2
    for ln in lines:  # every stdout line parses: pure JSONL
        doc = json.loads(ln)
        assert any(q["query_id"] == "watch_json_q"
                   for q in doc["queries"])


# ------------------------------------------- 5. debug bundles

def test_bundle_write_verify_and_corruption(tmp_path, armed_monitor):
    with monitor.query_span("bundle_q", mode="in-process"):
        pass
    out = str(tmp_path / "bundle")
    manifest = bundle.write_bundle(out, query_id="bundle_q")
    assert manifest["algo"] == "crc32"
    for member in ("metrics.txt", "conf.json", "queries.json",
                   "history.json", "ledger.json", "lockset.json",
                   "errors.json"):
        assert member in manifest["members"], member
        assert os.path.exists(os.path.join(out, member))
    assert bundle.verify_bundle(out) == []
    # corruption negative: flip one byte in one member -> detected
    from blaze_tpu.runtime.integrity import flip_byte_in_file

    flip_byte_in_file(os.path.join(out, "metrics.txt"), 3)
    problems = bundle.verify_bundle(out)
    assert any("checksum mismatch: metrics.txt" in p for p in problems)
    # a deleted member is a different, equally loud problem
    os.unlink(os.path.join(out, "conf.json"))
    assert any("missing member: conf.json" in p
               for p in bundle.verify_bundle(out))


def test_bundle_records_skipped_members(tmp_path, armed_monitor,
                                        monkeypatch):
    def _boom():
        raise RuntimeError("mid-rotation")

    monkeypatch.setattr(monitor, "render_prometheus", _boom)
    out = str(tmp_path / "bundle_skip")
    manifest = bundle.write_bundle(out)
    # best-effort: the member is absent but its absence is RECORDED
    assert "metrics.txt" not in manifest["members"]
    assert "mid-rotation" in manifest["skipped"]["metrics.txt"]
    assert bundle.verify_bundle(out) == []


def test_redact_conf_masks_values_keeps_keys():
    values = {"spark.ssl.keyPassword": "hunter2",
              "spark.blaze.api.token": "abc",
              "spark.blaze.scale": 2}
    red = bundle.redact_conf(values, patterns=["password", "token"])
    # the on-call sees WHICH keys were set, never the secrets
    assert red["spark.ssl.keyPassword"] == "***"
    assert red["spark.blaze.api.token"] == "***"
    assert red["spark.blaze.scale"] == 2
    # the conf-declared default patterns cover the usual suspects
    assert bundle.redact_conf({"a.secret.b": "x"})["a.secret.b"] == "***"
